"""TM3xx — JAX tracing hygiene (ops/ and crypto/batch.py).

Inside a jitted function arguments are tracers: Python `if`/`while` on
them either throws at trace time or — worse — bakes one branch into
the compiled kernel; `.item()`/`float()` force a device→host sync that
serializes the pipelined dispatch; and building shapes from traced
values re-specializes the kernel per call, defeating the bucketed-batch
cache that bounds compilations. Scope is ``[tool.tmlint] jax-paths``.

Parameters named in ``static_argnames``/``static_argnums`` are concrete
Python values at trace time — branching on them is the intended idiom
and is not flagged.
"""
from __future__ import annotations

import ast

from tendermint_tpu.lint.engine import Context, FuncInfo, Rule, attr_tail, dotted_name

_SHAPE_BUILDERS = {
    "arange",
    "zeros",
    "ones",
    "empty",
    "full",
    "eye",
    "tri",
    "linspace",
}
_ARRAY_MODULES = ("jnp", "np", "jax.numpy", "numpy")


_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")


def _traced_names_in(ctx: Context, fi: FuncInfo, expr: ast.AST) -> set[str]:
    """Parameter names of the jitted function referenced by `expr` that
    are NOT static (i.e. tracers at trace time).

    `x.shape` / `x.ndim` / `x.dtype` / `x.size` and `len(x)` ARE
    trace-time constants — the recommended way to derive sizes — so
    names reached only through those are not counted.
    """
    traced = fi.params - (fi.jit_static or set())
    found: set[str] = set()

    def rec(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return  # x.shape[...] etc: static metadata, prune the receiver
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        ):
            return  # len(tracer) is its static leading dim
        if isinstance(node, ast.Name) and node.id in traced:
            found.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            rec(child)

    rec(expr)
    return found


def _in_jax_scope(ctx: Context) -> FuncInfo | None:
    if not ctx.config.in_jax_scope(ctx.rel_path):
        return None
    return ctx.jit_func


class TM301PythonBranchOnTracer(Rule):
    code = "TM301"
    name = "python-branch-on-tracer"
    help = (
        "`if`/`while` on a traced argument inside jit either raises "
        "ConcretizationTypeError or silently specializes the kernel on "
        "the tracing-time value. Use jax.lax.cond/select/while_loop, or "
        "declare the argument static."
    )

    def visit_If(self, ctx: Context, node: ast.If) -> None:
        self._check(ctx, node, "if")

    def visit_While(self, ctx: Context, node: ast.While) -> None:
        self._check(ctx, node, "while")

    def _check(self, ctx: Context, node: ast.AST, kind: str) -> None:
        fi = _in_jax_scope(ctx)
        if fi is None:
            return
        names = _traced_names_in(ctx, fi, node.test)
        if names:
            ctx.report(
                self.code,
                node,
                f"Python `{kind}` on traced argument(s) "
                f"{', '.join(sorted(names))} inside a jitted function",
                "use jax.lax.cond / jnp.where / lax.while_loop, or add the "
                "argument to static_argnames",
            )


class TM302HostSyncInJit(Rule):
    code = "TM302"
    name = "host-sync-in-jit"
    help = (
        "`.item()` / `float()` / `device_get` inside jit forces the value "
        "to the host: a trace-time error at best, a per-call device sync "
        "that stalls the dispatch pipeline at worst. Keep values on "
        "device; convert only outside the jitted boundary."
    )

    def visit_Call(self, ctx: Context, node: ast.Call) -> None:
        fi = _in_jax_scope(ctx)
        if fi is None:
            return
        tail = attr_tail(node.func)
        if tail in ("item", "block_until_ready") and not node.args:
            ctx.report(
                self.code,
                node,
                f"host sync `.{tail}()` inside a jitted function",
                "return the array and convert at the call site",
            )
            return
        dotted = dotted_name(node.func)
        if dotted in ("jax.device_get", "jax.block_until_ready"):
            ctx.report(
                self.code,
                node,
                f"host sync `{dotted}(...)` inside a jitted function",
                "fetch outside the jitted boundary",
            )
            return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and _traced_names_in(ctx, fi, node.args[0])
        ):
            ctx.report(
                self.code,
                node,
                f"`{node.func.id}(...)` on a traced argument inside a "
                "jitted function",
                "keep it as an array (jnp.float32(...)/astype) or make "
                "the argument static",
            )


class TM303RuntimeShapeInJit(Rule):
    code = "TM303"
    name = "runtime-shape-in-jit"
    help = (
        "Array shapes inside jit must be trace-time constants; sizing one "
        "from a traced value either throws or re-specializes the kernel "
        "per distinct value — exactly the recompilation storm the "
        "bucketed-batch cache exists to prevent. Derive sizes from "
        "static args or `x.shape`."
    )

    def visit_Call(self, ctx: Context, node: ast.Call) -> None:
        fi = _in_jax_scope(ctx)
        if fi is None:
            return
        builder = None
        if isinstance(node.func, ast.Name) and node.func.id == "range":
            builder = "range"
        else:
            dotted = dotted_name(node.func)
            if dotted is not None and "." in dotted:
                mod, _, fn = dotted.rpartition(".")
                if fn in _SHAPE_BUILDERS and mod in _ARRAY_MODULES:
                    builder = dotted
        if builder is None:
            return
        names = set()
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            names |= _traced_names_in(ctx, fi, arg)
        if names:
            ctx.report(
                self.code,
                node,
                f"`{builder}(...)` sized from traced argument(s) "
                f"{', '.join(sorted(names))} inside a jitted function",
                "size from static_argnames values or a .shape, and bucket "
                "dynamic batch sizes before entering jit",
            )


RULES = [TM301PythonBranchOnTracer, TM302HostSyncInJit, TM303RuntimeShapeInJit]
