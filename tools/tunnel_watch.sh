#!/bin/bash
# Unattended TPU measurement pipeline: poll for the tunnel; the moment a
# device answers, run the round-4 measurement sequence and log everything.
# Decouples measurement from operator attention — a brief tunnel window
# still yields the bench number, the TPU correctness artifact, the kernel
# A/B and the device-only timing artifact (DEVICE_PROFILE).
#
# Steps run in priority order and each leaves a marker on success, so a
# tunnel that dies mid-sequence costs at most one step's timeout: the next
# window resumes at the first incomplete step instead of repeating finished
# work. The tunnel is re-probed before every step, and each step runs in
# its own process GROUP with a watchdog that kills the whole group on
# timeout — a hung jax RPC (a dead tunnel hangs forever, it never errors)
# cannot orphan a python that holds the device connection.
#
# Usage: nohup bash tools/tunnel_watch.sh &   (logs under tunnel_watch/)
set -u
cd "$(dirname "$0")/.."
OUT=tunnel_watch
ROUND=05
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*" | tee -a "$OUT/watch.log"; }

probe() {
    timeout 90 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

# run_step <name> <timeout_s> <cmd...>
# stdout -> $OUT/<name>.out, stderr -> $OUT/<name>.log. Skips if the done
# marker exists; re-probes first; marks done only on rc=0 so a failed step
# retries on the next tunnel window. Returns 1 only when the tunnel is
# gone (caller goes back to polling).
run_step() {
    local name="$1" tmo="$2"; shift 2
    [ -e "$OUT/done.$name" ] && return 0
    if ! probe; then
        log "$name: tunnel gone — back to polling"
        return 1
    fi
    log "$name: starting (timeout ${tmo}s)"
    setsid "$@" >"$OUT/$name.out" 2>"$OUT/$name.log" &
    local pid=$! rc waited=0
    while kill -0 "$pid" 2>/dev/null && [ "$waited" -lt "$tmo" ]; do
        sleep 5
        waited=$((waited + 5))
    done
    if kill -0 "$pid" 2>/dev/null; then
        # timeout: kill the whole process group (setsid made pgid=pid)
        kill -TERM -- "-$pid" 2>/dev/null
        sleep 10
        kill -KILL -- "-$pid" 2>/dev/null
        wait "$pid" 2>/dev/null   # reap: no zombie per timed-out step
        rc=124
        log "$name: TIMED OUT after ${tmo}s — process group killed"
    else
        wait "$pid"
        rc=$?
        log "$name: rc=$rc"
    fi
    [ "$rc" -eq 0 ] && touch "$OUT/done.$name"
    return 0
}

PREWARM_PY='
import sys, time
from tendermint_tpu.ops import kcache
kcache.enable_persistent_cache()
kcache.suppress_background_warm()
b = int(sys.argv[1])
t0 = time.time()
kcache.prewarm([b], background=False)
print(f"bucket {b} warm in {time.time()-t0:.1f}s", flush=True)
'

# Every bucket the sequence compiles, ascending: bench needs 128 (100-val
# commit), 1024 (1000-val), 12288 (pad of one 10k commit), 131072 (stream
# chunks); baseline config 3 adds 2048 (1040 sigs). Small buckets compile
# in well under a minute, so a brief window banks several.
PREWARM_BUCKETS="128 1024 2048 12288 131072"

all_done() {
    local s
    for b in $PREWARM_BUCKETS; do
        [ -e "$OUT/done.prewarm_$b" ] || return 1
    done
    for b in 1024 2560 10240 131072; do
        [ -e "$OUT/done.device_time_$b" ] || return 1
    done
    for s in quick bench1 bench2 artifact kernel_ab baseline; do
        [ -e "$OUT/done.$s" ] || return 1
    done
    return 0
}

log "watch started (round $ROUND)"
while true; do
    if probe; then
        log "TUNNEL UP — running sequence (resumes at first incomplete step)"
        # 0. FIRST 60 SECONDS RULE (r4 postmortem: a 1-minute window banked
        #    nothing because the first device action was a flagship-shape
        #    compile): the very first step of any fresh window is the
        #    SMALLEST meaningful measurement. quick_bench escalates
        #    100 -> 1000 -> 10000 validators, printing a JSON line and
        #    updating $OUT/banked_quick.json after EVERY completed size, so
        #    however short the window, the largest finished size is banked
        #    and bench.py can replay it (labelled) if the driver's
        #    end-of-round run hits a dead tunnel.
        run_step quick 1500 python -u -m benchmarks.quick_bench || continue
        [ -e "$OUT/done.quick" ] && \
            log "quick banked: $(tail -1 "$OUT/quick.out" 2>/dev/null)"
        # 1. warm kernel caches INCREMENTALLY, smallest bucket first: each
        #    completed compile lands in the persistent XLA cache + export
        #    blobs immediately, so a window that dies mid-sequence still
        #    banks every finished bucket (the 03:16 r4 window died inside
        #    a monolithic 131072 prewarm and banked nothing). The driver's
        #    end-of-round `python bench.py` reads the same on-disk cache.
        for b in $PREWARM_BUCKETS; do
            tmo=600; [ "$b" -ge 65536 ] && tmo=1500
            run_step "prewarm_$b" "$tmo" python -c "$PREWARM_PY" "$b" || continue 2
        done
        # 2. headline bench twice: first may pay residual warmup; the
        #    second is the steady-state number. JSON lands in benchN.out.
        for i in 1 2; do
            run_step "bench$i" 1800 python bench.py || continue 2
            [ -e "$OUT/done.bench$i" ] && \
                log "bench$i JSON: $(cat "$OUT/bench$i.out" 2>/dev/null)"
        done
        # 3. real-TPU correctness artifact (device-gated kernel parity
        #    tests + kernel_compare 1024/10240) -> TPUTEST_r04.log
        run_step artifact 2700 bash tools/tpu_artifact.sh "$ROUND" || continue
        # 4. kernel A/B at the one shape the artifact doesn't cover —
        #    the radix-4/radix-8 promotion decision input (VERDICT r3 #1)
        run_step kernel_ab 1800 python -m benchmarks.kernel_compare 131072 || continue
        if [ -e "$OUT/done.kernel_ab" ] && [ ! -e "KERNEL_AB_r${ROUND}.log" ]; then
            # commit-able evidence: must not live only in the gitignored
            # watch dir (1024/10240 shapes are in TPUTEST_r04.log already)
            { echo "== kernel_compare 131072 (A/B promotion input) =="
              date -u +"%Y-%m-%dT%H:%M:%SZ"
              cat "$OUT/kernel_ab.out"; } >"KERNEL_AB_r${ROUND}.log"
        fi
        # 5. tunnel-independent device-only timing per bucket x kernel
        #    variant (VERDICT r3 #2) -> DEVICE_PROFILE_r04.md. One step
        #    PER BUCKET so a window that dies mid-sequence still banks
        #    every completed bucket's numbers; the artifact assembles
        #    from whatever buckets have finished so far (and re-assembles
        #    as later windows add more). device_time exits nonzero if no
        #    variant produced a number, so a done marker can't enshrine
        #    a stub.
        for b in 1024 2560 10240 131072; do
            run_step "device_time_$b" 1500 \
                python -u -m benchmarks.device_time "$b" || continue 2
        done
        dt_done=""
        for b in 1024 2560 10240 131072; do
            [ -e "$OUT/done.device_time_$b" ] && dt_done="$dt_done $b"
        done
        if [ -n "$dt_done" ]; then
            { echo "# DEVICE_PROFILE — round $ROUND"
              echo
              date -u +"%Y-%m-%dT%H:%M:%SZ"
              echo "buckets completed:$dt_done"
              echo
              for b in $dt_done; do
                  cat "$OUT/device_time_$b.out"
                  echo
              done; } >"DEVICE_PROFILE_r${ROUND}.md"
        fi
        # 6. baseline configs — all FIVE (r4 verdict weak #3: config 4 was
        #    skipped); 4 runs its default 100x500 shape here to stay inside
        #    the step budget (the --full 500x2000 shape is a notes-side run)
        run_step baseline 2700 python -m benchmarks.baseline_configs 1 2 3 4 5 || continue
        if all_done; then
            log "sequence complete — logs in $OUT/"
            exit 0
        fi
        log "window ended with incomplete/failed steps — will retry"
    else
        log "tunnel still down"
    fi
    # 45s poll (was 120): windows are rare and short, so time-to-detection
    # is part of the capture budget — a 90s probe + 45s sleep bounds the
    # worst-case missed head of a window at ~2.2 min.
    sleep 45
done
