#!/bin/bash
# Unattended TPU measurement pipeline: poll for the tunnel; the moment a
# device answers, run the full round-3 measurement sequence and log
# everything. Decouples measurement from operator attention — a brief
# tunnel window still yields the bench number, the TPU correctness
# artifact and the baseline table.
#
# Usage: nohup bash tools/tunnel_watch.sh &   (logs under tunnel_watch/)
set -u
cd "$(dirname "$0")/.."
OUT=tunnel_watch
mkdir -p "$OUT"
log() { echo "[$(date -u +%H:%M:%S)] $*" | tee -a "$OUT/watch.log"; }

probe() {
    timeout 90 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

log "watch started"
while true; do
    if probe; then
        log "TUNNEL UP — starting measurement sequence"
        # 1. warm the kernel caches for the bench bucket so the headline
        #    run (and the driver's later run) hits warm compiles
        log "prewarm (cold compile ~2-4 min on a fresh cache)"
        timeout 900 python - >"$OUT/prewarm.log" 2>&1 <<'EOF'
from tendermint_tpu.ops import kcache
kcache.enable_persistent_cache()
kcache.suppress_background_warm()
kcache.prewarm([131072], background=False)
print("prewarm done")
EOF
        log "prewarm rc=$?"
        # 2. the headline bench (twice: first may still pay residual
        #    warmup; the second is the steady-state number)
        for i in 1 2; do
            timeout 1800 python bench.py \
                >"$OUT/bench_$i.json" 2>"$OUT/bench_$i.log"
            log "bench run $i rc=$? -> $(cat "$OUT/bench_$i.json" 2>/dev/null)"
        done
        # 3. the real-TPU correctness artifact
        timeout 2700 bash tools/tpu_artifact.sh 03 >"$OUT/artifact.log" 2>&1
        log "tpu_artifact rc=$? (TPUTEST_r03.log written)"
        # 4. baseline configs over the tunnel (1=anchor 2=commit
        #    3=validate_block 5=streamed voteset; 4 is slow to build)
        timeout 2700 python -m benchmarks.baseline_configs 1 2 3 5 \
            >"$OUT/baseline.log" 2>&1
        log "baseline_configs rc=$?"
        log "sequence complete — logs in $OUT/"
        exit 0
    fi
    log "tunnel still down"
    sleep 120
done
