#!/bin/bash
# Produce the real-TPU correctness artifact (r2 VERDICT next #4):
# device-gated kernel parity tests + the XLA-vs-Pallas kernel comparison,
# logged to TPUTEST_r<N>.log for the judge. Run only with a live tunnel
# (probe first: timeout 90 python -c 'import jax; print(jax.devices())').
#
# Usage: bash tools/tpu_artifact.sh [round]   (default round: 03)
set -u
cd "$(dirname "$0")/.."
ROUND="${1:-03}"
LOG="TPUTEST_r${ROUND}.log"

{
  echo "== TPU correctness artifact, round ${ROUND} =="
  date -u +"%Y-%m-%dT%H:%M:%SZ"
  python - <<'EOF'
import jax
d = jax.devices()[0]
print(f"device: {d.platform} ({d.device_kind})")
EOF
  echo
  echo "== device-gated kernel parity tests (TMTPU_TPU_TESTS=1) =="
  TMTPU_TPU_TESTS=1 python -m pytest tests/test_ops_verify.py tests/test_ops_secp.py -v 2>&1 | tail -40
  echo "pytest rc=$?"
  echo
  echo "== XLA vs Pallas kernel comparison on device =="
  python benchmarks/kernel_compare.py 1024 10240 2>&1 | tail -30
  echo "kernel_compare rc=$?"
} | tee "$LOG"
