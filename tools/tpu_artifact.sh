#!/bin/bash
# Produce the real-TPU correctness artifact (r2 VERDICT next #4):
# device-gated kernel parity tests + the XLA-vs-Pallas kernel comparison,
# logged to TPUTEST_r<N>.log for the judge. Run only with a live tunnel
# (probe first: timeout 90 python -c 'import jax; print(jax.devices())').
#
# Exits nonzero if the parity tests or the kernel comparison fail, so
# callers (tools/tunnel_watch.sh resume logic) retry on the next window
# instead of enshrining a broken artifact.
#
# Usage: bash tools/tpu_artifact.sh [round]   (default round: 04)
set -u
cd "$(dirname "$0")/.."
ROUND="${1:-04}"
LOG="TPUTEST_r${ROUND}.log"
TMP="$(mktemp)"
trap 'rm -f "$TMP"' EXIT

{
  OVERALL=0
  echo "== TPU correctness artifact, round ${ROUND} =="
  date -u +"%Y-%m-%dT%H:%M:%SZ"
  timeout 120 python - <<'EOF'
import jax
d = jax.devices()[0]
print(f"device: {d.platform} ({d.device_kind})")
EOF
  if [ $? -ne 0 ]; then
    # dead tunnel: the device-gated pytest below would hang forever, not
    # error — bail out now so the caller's watchdog window isn't burned
    echo "device unreachable — aborting artifact run"
    exit 1
  fi
  echo
  echo "== device-gated kernel parity tests (TMTPU_TPU_TESTS=1) =="
  TMTPU_TPU_TESTS=1 python -m pytest tests/test_ops_verify.py tests/test_ops_secp.py -v >"$TMP" 2>&1
  RC=$?
  tail -40 "$TMP"
  echo "pytest rc=$RC"
  [ "$RC" -eq 0 ] || OVERALL=1
  echo
  echo "== XLA vs Pallas kernel comparison on device =="
  python -m benchmarks.kernel_compare 1024 10240 >"$TMP" 2>&1
  RC=$?
  tail -30 "$TMP"
  echo "kernel_compare rc=$RC"
  [ "$RC" -eq 0 ] || OVERALL=1
  echo
  echo "overall rc=$OVERALL"
  exit $OVERALL
} | tee "$LOG"
exit "${PIPESTATUS[0]}"
