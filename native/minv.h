// Montgomery-trick batch inversion, shared by the ed25519 and secp256k1
// cores: out[i] = 1 / *elems[i] for n field elements, at the cost of ONE
// field inversion + 3(n-1) multiplications. The forward prefix-product /
// invert / backward-unwind index discipline lives HERE once — five call
// sites used to hand-roll it, and a one-line transposition in any copy
// silently couples results across elements (for the verify paths, across
// signatures' verdicts).
//
// Requirements: every *elems[i] is nonzero (callers guard — a zero
// poisons the whole chain); n == 0 is a no-op. Mul must tolerate output
// aliasing either input (all three field muls in this repo do).
#pragma once
#include <cstddef>

namespace tmnative {

// Mul: void(T&, const T&, const T&); Inv: void(T&, const T&).
template <typename T, typename Mul, typename Inv>
inline void batch_invert(T* const* elems, T* out, size_t n, const T& one,
                         Mul&& mul, Inv&& inv) {
    if (n == 0) return;
    T acc = one;
    for (size_t i = 0; i < n; i++) {
        out[i] = acc;  // product of elems[0..i-1]
        mul(acc, acc, *elems[i]);
    }
    T accinv;
    inv(accinv, acc);
    for (size_t i = n; i-- > 0;) {
        T t;
        mul(t, accinv, out[i]);          // 1 / *elems[i]
        mul(accinv, accinv, *elems[i]);  // strip elems[i] from the chain
        out[i] = t;
    }
}

}  // namespace tmnative
