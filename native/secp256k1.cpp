// secp256k1 ECDSA verification, clean-room C++.
//
// The native-parity replacement for the reference's vendored libsecp256k1
// (crypto/secp256k1/internal, 17.5k LoC of C): this framework implements
// the VERIFY path natively, in tendermint's wire format — 33-byte
// compressed pubkey, 64-byte r||s signature with the low-S rule
// (reference secp256k1_nocgo.go:40-50), SHA-256 message digest.
//
// Signing is deliberately NOT reimplemented here. Every scalar
// multiplication in this file is VARIABLE-TIME (wNAF recoding, digit-
// indexed table loads, data-dependent branches) — safe for verification,
// whose inputs are public, and where variable-time is the whole speed
// story. A signer runs the same math on SECRET nonces and keys, where
// those exact properties are a timing/cache side channel; doing it right
// means constant-time ladders and cmov table scans — a different,
// hardened codebase (what libsecp256k1's signing half actually is).
// Signing therefore stays on the vetted OpenSSL path behind the Python
// key objects (crypto/secp256k1.py), where it is nowhere near a hot
// loop: a validator signs ONE vote per consensus step and verifies
// hundreds to thousands.
//
// Field arithmetic: 4x64 limbs, reduction by p = 2^256 - 0x1000003D1.
// Scalar arithmetic mod n: folding reduction by c = 2^256 - n (129 bits).
// Points: Jacobian coordinates; verification runs one interleaved
// Strauss double-scalar multiplication (wNAF(8) over a static affine
// G table + wNAF(5) over a per-key Jacobian table) and compares R.x
// against r in Jacobian coordinates, so the whole verify needs no field
// inversion. Measured 2.6x the OpenSSL generic-EC path this backs up
// (OpenSSL has no specialized secp256k1 code) on one core — README's
// round-4 native-core table has the numbers.
#include <cstdint>
#include <cstring>
#include "minv.h"
#include "pubcache.h"
#include "sha2.h"
#include "wnaf.h"

namespace tmnative {

typedef unsigned __int128 u128;

// ------------------------------------------------------------- field (mod p)

struct Fp {
    uint64_t v[4];  // little-endian limbs
};

static const uint64_t P[4] = {0xFFFFFFFEFFFFFC2Full, 0xFFFFFFFFFFFFFFFFull,
                              0xFFFFFFFFFFFFFFFFull, 0xFFFFFFFFFFFFFFFFull};
static const uint64_t PC = 0x1000003D1ull;  // 2^256 mod p

static int fp_cmp_raw(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void fp_sub_p(uint64_t a[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a[i] - P[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static void fp_norm(Fp& a) {
    if (fp_cmp_raw(a.v, P) >= 0) fp_sub_p(a.v);
}

static void fp_add(Fp& o, const Fp& a, const Fp& b) {
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 s = (u128)a.v[i] + b.v[i] + carry;
        o.v[i] = (uint64_t)s;
        carry = (uint64_t)(s >> 64);
    }
    if (carry) {  // wrapped 2^256: add PC
        u128 c = PC;
        for (int i = 0; i < 4 && c; i++) {
            u128 s = (u128)o.v[i] + c;
            o.v[i] = (uint64_t)s;
            c = (uint64_t)(s >> 64);
        }
    }
    fp_norm(o);
}

static void fp_sub(Fp& o, const Fp& a, const Fp& b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a.v[i] - b.v[i] - borrow;
        o.v[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    if (borrow) {  // add p back
        u128 carry = 0;
        for (int i = 0; i < 4; i++) {
            u128 s = (u128)o.v[i] + P[i] + carry;
            o.v[i] = (uint64_t)s;
            carry = (uint64_t)(s >> 64);
        }
    }
}

// reduce an 8-limb (512-bit) value mod p: value = lo + hi*2^256 ≡
// lo + hi*PC, folded twice (shared by fp_mul and fp_sq)
static void fp_fold(Fp& o, const uint64_t t[8]) {
    uint64_t r[5] = {t[0], t[1], t[2], t[3], 0};
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 cur = (u128)r[i] + (u128)t[4 + i] * PC + carry;
        r[i] = (uint64_t)cur;
        carry = (uint64_t)(cur >> 64);
    }
    r[4] = (uint64_t)carry;
    // second fold of the (small) top limb
    u128 c2 = (u128)r[4] * PC;
    uint64_t res[4] = {r[0], r[1], r[2], r[3]};
    for (int i = 0; i < 4 && c2; i++) {
        u128 s = (u128)res[i] + (uint64_t)c2;
        res[i] = (uint64_t)s;
        c2 = (c2 >> 64) + (s >> 64);
    }
    memcpy(o.v, res, sizeof res);
    fp_norm(o);
}

static void fp_mul(Fp& o, const Fp& a, const Fp& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a.v[i] * b.v[j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        t[i + 4] += (uint64_t)carry;
    }
    fp_fold(o, t);
}

// dedicated squaring: 10 64x64 products (6 cross, doubled, + 4 diagonal)
// vs the general multiply's 16 — squarings are ~60% of the verify loop's
// field ops (5 per point doubling), so this is a measured ~7% whole-
// verify saving, not a micro-nicety
static void fp_sq(Fp& o, const Fp& a) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 3; i++) {  // cross products a[i]*a[j], i < j
        u128 carry = 0;
        for (int j = i + 1; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a.v[i] * a.v[j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        int k = i + 4;
        while (carry) {  // t[i+4] may hold an earlier row's carry
            u128 s = (u128)t[k] + carry;
            t[k] = (uint64_t)s;
            carry = (uint64_t)(s >> 64);
            k++;
        }
    }
    // double the cross sum (it is < 2^511, so no carry out of t[7])
    uint64_t cb = 0;
    for (int i = 0; i < 8; i++) {
        uint64_t nc = t[i] >> 63;
        t[i] = (t[i] << 1) | cb;
        cb = nc;
    }
    // add the diagonals a[i]^2 at position 2i
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a.v[i] * a.v[i];
        u128 s = (u128)t[2 * i] + (uint64_t)d;
        t[2 * i] = (uint64_t)s;
        u128 carry = (s >> 64) + (uint64_t)(d >> 64);
        int k = 2 * i + 1;
        while (carry && k < 8) {  // k==8 unreachable: a^2 < 2^512
            u128 s2 = (u128)t[k] + carry;
            t[k] = (uint64_t)s2;
            carry = (uint64_t)(s2 >> 64);
            k++;
        }
    }
    fp_fold(o, t);
}

static void fp_pow(Fp& o, const Fp& a, const uint64_t e[4]) {
    Fp result = {{1, 0, 0, 0}}, base = a;
    for (int i = 0; i < 256; i++) {
        if ((e[i / 64] >> (i % 64)) & 1) fp_mul(result, result, base);
        fp_sq(base, base);
    }
    o = result;
}

static void fp_invert(Fp& o, const Fp& a) {
    uint64_t e[4];
    memcpy(e, P, sizeof e);
    e[0] -= 2;  // p - 2 (no borrow: low limb ends ...C2F)
    fp_pow(o, a, e);
}

static bool fp_sqrt(Fp& o, const Fp& a) {  // p ≡ 3 (mod 4)
    uint64_t e[4];
    memcpy(e, P, sizeof e);
    // (p+1)/4: add 1 then shift right 2
    e[0] += 1;
    for (int i = 0; i < 4; i++) {
        e[i] >>= 2;
        if (i < 3) e[i] |= e[i + 1] << 62;
    }
    fp_pow(o, a, e);
    Fp chk;
    fp_sq(chk, o);
    return memcmp(chk.v, a.v, sizeof chk.v) == 0;
}

static bool fp_iszero(const Fp& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static void fp_frombytes_be(Fp& o, const uint8_t in[32]) {
    for (int i = 0; i < 4; i++) {
        o.v[3 - i] = 0;
        for (int j = 0; j < 8; j++) o.v[3 - i] = (o.v[3 - i] << 8) | in[8 * i + j];
    }
}

static void fp_tobytes_be(uint8_t out[32], const Fp& a) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = uint8_t(a.v[3 - i] >> (56 - 8 * j));
}

// ------------------------------------------------------------ scalars (mod n)

static const uint64_t N[4] = {0xBFD25E8CD0364141ull, 0xBAAEDCE6AF48A03Bull,
                              0xFFFFFFFFFFFFFFFEull, 0xFFFFFFFFFFFFFFFFull};
// n/2 for the low-S rule
static const uint64_t NHALF[4] = {0xDFE92F46681B20A0ull, 0x5D576E7357A4501Dull,
                                  0xFFFFFFFFFFFFFFFFull, 0x7FFFFFFFFFFFFFFFull};

struct Sc {
    uint64_t v[4];
};

static int sc_cmp_raw(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void sc_sub_n(uint64_t a[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a[i] - N[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static bool sc_iszero(const Sc& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static void sc_frombytes_be(Sc& o, const uint8_t in[32]) {
    for (int i = 0; i < 4; i++) {
        o.v[3 - i] = 0;
        for (int j = 0; j < 8; j++) o.v[3 - i] = (o.v[3 - i] << 8) | in[8 * i + j];
    }
    while (sc_cmp_raw(o.v, N) >= 0) sc_sub_n(o.v);
}

// c = 2^256 - n = 0x1_45512319_50B75FC4_402DA173_2FC9BEBF (129 bits):
// value mod n folds as lo + hi*c, shrinking ~127 bits per fold.
static const uint64_t NC[3] = {0x402DA1732FC9BEBFull, 0x4551231950B75FC4ull,
                               1ull};

// reduce an 8-limb (512-bit) value mod n into o
static void sc_reduce_wide(Sc& o, const uint64_t t[8]) {
    // working value: up to 7 limbs across folds
    uint64_t v[8];
    memcpy(v, t, sizeof v);
    int top = 8;  // limbs in use
    while (top > 4) {
        int hi_limbs = top - 4;
        uint64_t hi[4] = {0, 0, 0, 0};
        memcpy(hi, v + 4, hi_limbs * sizeof(uint64_t));
        // v = v[0..3] + hi * c   (hi*c has at most hi_limbs+3 limbs)
        uint64_t acc[8] = {v[0], v[1], v[2], v[3], 0, 0, 0, 0};
        for (int i = 0; i < hi_limbs; i++) {
            u128 carry = 0;
            for (int j = 0; j < 3; j++) {
                u128 cur = (u128)acc[i + j] + (u128)hi[i] * NC[j] + carry;
                acc[i + j] = (uint64_t)cur;
                carry = (uint64_t)(cur >> 64);
            }
            int k = i + 3;
            while (carry) {
                u128 cur = (u128)acc[k] + carry;
                acc[k] = (uint64_t)cur;
                carry = (uint64_t)(cur >> 64);
                k++;
            }
        }
        memcpy(v, acc, sizeof v);
        top = 8;
        while (top > 4 && v[top - 1] == 0) top--;
    }
    while (sc_cmp_raw(v, N) >= 0) sc_sub_n(v);
    memcpy(o.v, v, 4 * sizeof(uint64_t));
}

// o = a*b mod n — 512-bit schoolbook product + folding reduction
static void sc_mul(Sc& o, const Sc& a, const Sc& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a.v[i] * b.v[j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        t[i + 4] += (uint64_t)carry;
    }
    sc_reduce_wide(o, t);
}

static void sc_invert(Sc& o, const Sc& a) {  // Fermat: a^(n-2), 4-bit windows
    Sc table[16];  // table[i] = a^i (i >= 1)
    table[1] = a;
    for (int i = 2; i < 16; i++) sc_mul(table[i], table[i - 1], a);
    uint64_t e[4];
    memcpy(e, N, sizeof e);
    e[0] -= 2;
    Sc result = {{1, 0, 0, 0}};
    bool started = false;
    for (int nib = 63; nib >= 0; nib--) {
        if (started)
            for (int d = 0; d < 4; d++) sc_mul(result, result, result);
        int idx = (e[nib / 16] >> (4 * (nib % 16))) & 0xF;
        if (idx) {
            if (started)
                sc_mul(result, result, table[idx]);
            else {
                result = table[idx];
                started = true;
            }
        }
    }
    o = result;
}

// --------------------------------------------------------------- points

struct Jac {  // Jacobian: x = X/Z^2, y = Y/Z^3; Z = 0 => infinity
    Fp X, Y, Z;
};

static const Fp FP_B = {{7, 0, 0, 0}};
static const Fp FP_ONE = {{1, 0, 0, 0}};
static const Fp GX = {{0x59F2815B16F81798ull, 0x029BFCDB2DCE28D9ull,
                       0x55A06295CE870B07ull, 0x79BE667EF9DCBBACull}};
static const Fp GY = {{0x9C47D08FFB10D4B8ull, 0xFD17B448A6855419ull,
                       0x5DA4FBFC0E1108A8ull, 0x483ADA7726A3C465ull}};

static void jac_infinity(Jac& o) {
    memset(&o, 0, sizeof o);
    o.X.v[0] = 1;
    o.Y.v[0] = 1;
}

static bool jac_is_infinity(const Jac& p) { return fp_iszero(p.Z); }

static void jac_double(Jac& o, const Jac& p) {
    if (jac_is_infinity(p) || fp_iszero(p.Y)) {
        jac_infinity(o);
        return;
    }
    Fp A, B, C, D, X3, Y3, Z3, t;
    fp_sq(A, p.X);                       // A = X^2
    fp_sq(B, p.Y);                       // B = Y^2
    fp_sq(C, B);                         // C = B^2
    // D = 2((X+B)^2 - A - C)
    fp_add(t, p.X, B);
    fp_sq(t, t);
    fp_sub(t, t, A);
    fp_sub(t, t, C);
    fp_add(D, t, t);
    Fp E, F;
    fp_add(E, A, A);
    fp_add(E, E, A);                     // E = 3A (a = 0 curve)
    fp_sq(F, E);                         // F = E^2
    fp_sub(X3, F, D);
    fp_sub(X3, X3, D);                   // X3 = F - 2D
    fp_sub(t, D, X3);
    fp_mul(t, E, t);
    Fp C8;
    fp_add(C8, C, C);
    fp_add(C8, C8, C8);
    fp_add(C8, C8, C8);                  // 8C
    fp_sub(Y3, t, C8);                   // Y3 = E(D - X3) - 8C
    fp_mul(Z3, p.Y, p.Z);
    fp_add(Z3, Z3, Z3);                  // Z3 = 2 Y Z
    o.X = X3; o.Y = Y3; o.Z = Z3;
}

static void jac_add(Jac& o, const Jac& p, const Jac& q) {
    if (jac_is_infinity(p)) { o = q; return; }
    if (jac_is_infinity(q)) { o = p; return; }
    Fp Z1Z1, Z2Z2, U1, U2, S1, S2, t;
    fp_sq(Z1Z1, p.Z);
    fp_sq(Z2Z2, q.Z);
    fp_mul(U1, p.X, Z2Z2);
    fp_mul(U2, q.X, Z1Z1);
    fp_mul(t, q.Z, Z2Z2);
    fp_mul(S1, p.Y, t);
    fp_mul(t, p.Z, Z1Z1);
    fp_mul(S2, q.Y, t);
    Fp H, R;
    fp_sub(H, U2, U1);
    fp_sub(R, S2, S1);
    if (fp_iszero(H)) {
        if (fp_iszero(R)) { jac_double(o, p); return; }
        jac_infinity(o);  // P + (-P)
        return;
    }
    Fp H2, H3, U1H2, X3, Y3, Z3;
    fp_sq(H2, H);
    fp_mul(H3, H2, H);
    fp_mul(U1H2, U1, H2);
    fp_sq(X3, R);
    fp_sub(X3, X3, H3);
    fp_sub(X3, X3, U1H2);
    fp_sub(X3, X3, U1H2);                // X3 = R^2 - H^3 - 2 U1 H^2
    fp_sub(t, U1H2, X3);
    fp_mul(t, R, t);
    Fp S1H3;
    fp_mul(S1H3, S1, H3);
    fp_sub(Y3, t, S1H3);                 // Y3 = R(U1 H^2 - X3) - S1 H^3
    fp_mul(Z3, p.Z, q.Z);
    fp_mul(Z3, Z3, H);                   // Z3 = Z1 Z2 H
    o.X = X3; o.Y = Y3; o.Z = Z3;
}

struct Aff {  // affine point (never infinity in the tables below)
    Fp x, y;
};

// mixed addition: o = p + q with q affine (8 mul + 3 sq vs jac_add's 12+4)
static void jac_madd(Jac& o, const Jac& p, const Aff& q) {
    if (jac_is_infinity(p)) {
        o.X = q.x;
        o.Y = q.y;
        memset(&o.Z, 0, sizeof o.Z);
        o.Z.v[0] = 1;
        return;
    }
    Fp Z1Z1, U2, S2, t;
    fp_sq(Z1Z1, p.Z);
    fp_mul(U2, q.x, Z1Z1);
    fp_mul(t, p.Z, Z1Z1);
    fp_mul(S2, q.y, t);
    Fp H, R;
    fp_sub(H, U2, p.X);
    fp_sub(R, S2, p.Y);
    if (fp_iszero(H)) {
        if (fp_iszero(R)) {
            jac_double(o, p);
            return;
        }
        jac_infinity(o);
        return;
    }
    Fp H2, H3, V, X3, Y3, Z3;
    fp_sq(H2, H);
    fp_mul(H3, H2, H);
    fp_mul(V, p.X, H2);
    fp_sq(X3, R);
    fp_sub(X3, X3, H3);
    fp_sub(X3, X3, V);
    fp_sub(X3, X3, V);                  // X3 = R^2 - H^3 - 2V
    fp_sub(t, V, X3);
    fp_mul(t, R, t);
    Fp YH3;
    fp_mul(YH3, p.Y, H3);
    fp_sub(Y3, t, YH3);                 // Y3 = R(V - X3) - Y1 H^3
    fp_mul(Z3, p.Z, H);                 // Z3 = Z1 H
    o.X = X3; o.Y = Y3; o.Z = Z3;
}

static int wnaf(int8_t out[257], const Sc& k, int w) {
    return wnaf_digits(out, k.v, w);
}

// ------------------------------------------------------ GLV endomorphism
//
// secp256k1 has the efficient endomorphism phi(x, y) = (beta*x, y) with
// phi(P) = [lambda]P (beta^3 = 1 mod p, lambda^3 = 1 mod n). Splitting a
// scalar k = k1 + k2*lambda with |k1|,|k2| ~ sqrt(n) turns the verify's
// double-scalar multiplication into four ~130-bit streams over one
// HALF-length doubling chain — the signature optimization of
// libsecp256k1, clean-roomed here. Every constant and the split algebra
// are VERIFIED at startup (beta/lambda order checks, phi(G) == [lambda]G,
// and k1 + k2*lambda == k over a scalar sweep); any mismatch sets
// glv_ok=false and verification falls back to the 2-stream Strauss loop —
// correctness can never depend on these digits, only speed.

static const uint64_t LAMBDA[4] = {
    0xDF02967C1B23BD72ull, 0x122E22EA20816678ull,
    0xA5261C028812645Aull, 0x5363AD4CC05C30E0ull};
static const Fp BETA = {{0xC1396C28719501EEull, 0x9CF0497512F58995ull,
                         0x6E64479EAC3434E9ull, 0x7AE96A2B657C0710ull}};
// lattice basis: a1 + b1*lambda = 0 (mod n) with b1 NEGATIVE (B1ABS = -b1),
// a2 + b2*lambda = 0 (mod n) with b2 = a1 (published GLV basis for this
// curve; self-checked below)
static const uint64_t A1[4] = {0xE86C90E49284EB15ull, 0x3086D221A7D46BCDull, 0, 0};
static const uint64_t B1ABS[4] = {0x6F547FA90ABFE4C3ull, 0xE4437ED6010E8828ull, 0, 0};
static const uint64_t A2[4] = {0x57C1108D9D44CFD8ull, 0x14CA50F7A8E2F3F6ull, 1, 0};

struct Glv {
    bool ok = false;
    // g1 = round(2^384 * b2 / n), g2 = round(2^384 * |b1| / n): the split's
    // rounded quotients become mul+shift (computed at startup by long
    // division — no transcribed magic quotients to get wrong)
    uint64_t g1[5] = {0};
    uint64_t g2[5] = {0};
};
static Glv GLV;

// num = b << 384 divided by n, rounded to nearest: restoring division
// over 10 limbs, runs once at startup
static void _div_round_shift384(uint64_t out[5], const uint64_t b[4]) {
    uint64_t num[11] = {0};  // b << 384
    for (int i = 0; i < 4; i++) num[i + 6] = b[i];
    uint64_t q[11] = {0}, r[5] = {0};  // remainder < n fits 4, +1 slack
    for (int bit = 64 * 10 - 1; bit >= 0; bit--) {
        // r = (r << 1) | num_bit
        for (int i = 4; i > 0; i--) r[i] = (r[i] << 1) | (r[i - 1] >> 63);
        r[0] = (r[0] << 1) | ((num[bit / 64] >> (bit % 64)) & 1);
        // if r >= n: r -= n; q_bit = 1
        bool ge = r[4] != 0 || sc_cmp_raw(r, N) >= 0;
        if (ge) {
            u128 borrow = 0;
            for (int i = 0; i < 5; i++) {
                u128 d = (u128)r[i] - (i < 4 ? N[i] : 0) - borrow;
                r[i] = (uint64_t)d;
                borrow = (d >> 64) ? 1 : 0;
            }
            q[bit / 64] |= 1ull << (bit % 64);
        }
    }
    // round: if 2r >= n, q += 1
    uint64_t r2[5];
    for (int i = 4; i > 0; i--) r2[i] = (r[i] << 1) | (r[i - 1] >> 63);
    r2[0] = r[0] << 1;
    if (r2[4] != 0 || sc_cmp_raw(r2, N) >= 0) {
        u128 carry = 1;
        for (int i = 0; i < 11 && carry; i++) {
            u128 s = (u128)q[i] + carry;
            q[i] = (uint64_t)s;
            carry = (uint64_t)(s >> 64);
        }
    }
    memcpy(out, q, 5 * sizeof(uint64_t));
}

// c = (k * g + 2^383) >> 384 for a 5-limb g; c fits ~130 bits (3 limbs)
static void _mul_shift384(uint64_t c[4], const uint64_t k[4],
                          const uint64_t g[5]) {
    uint64_t t[9] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 5; j++) {
            u128 cur = (u128)t[i + j] + (u128)k[i] * g[j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        t[i + 5] += (uint64_t)carry;
    }
    // + 2^383 (bit 383 = limb 5, bit 63), then >> 384 (take limbs 6..8)
    u128 carry = (u128)t[5] + (1ull << 63);
    carry >>= 64;
    for (int i = 6; i < 9 && carry; i++) {
        u128 s = (u128)t[i] + carry;
        t[i] = (uint64_t)s;
        carry = (uint64_t)(s >> 64);
    }
    c[0] = t[6];
    c[1] = t[7];
    c[2] = t[8];
    c[3] = 0;
}

// 4x4-limb schoolbook product — one definition for both accumulators
static void _mul_4x4(uint64_t p[8], const uint64_t c[4], const uint64_t m[4]) {
    memset(p, 0, 8 * sizeof(uint64_t));
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)p[i + j] + (u128)c[i] * m[j] + carry;
            p[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        p[i + 4] += (uint64_t)carry;
    }
}

// signed 5-limb two's-complement helpers for the split accumulation
static void _acc_submul(uint64_t acc[5], const uint64_t c[4],
                        const uint64_t m[4]) {
    uint64_t p[8];
    _mul_4x4(p, c, m);
    u128 borrow = 0;
    for (int i = 0; i < 5; i++) {
        u128 d = (u128)acc[i] - p[i] - borrow;
        acc[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static void _acc_addmul(uint64_t acc[5], const uint64_t c[4],
                        const uint64_t m[4]) {
    uint64_t p[8];
    _mul_4x4(p, c, m);
    u128 carry = 0;
    for (int i = 0; i < 5; i++) {
        u128 s = (u128)acc[i] + p[i] + carry;
        acc[i] = (uint64_t)s;
        carry = (uint64_t)(s >> 64);
    }
}

// two's-complement 5-limb -> (sign, |value| in 4 limbs); returns false if
// the magnitude reaches 2^133 (a correct split stays under ~2^129; an
// anomalous one makes the caller fall back to the 2-stream path)
static bool _acc_to_signed(const uint64_t acc[5], int& sign,
                           uint64_t mag[4]) {
    if (acc[4] >> 63) {  // negative
        uint64_t neg[5];
        u128 carry = 1;
        for (int i = 0; i < 5; i++) {
            u128 s = (u128)(~acc[i]) + carry;
            neg[i] = (uint64_t)s;
            carry = (uint64_t)(s >> 64);
        }
        sign = -1;
        memcpy(mag, neg, 4 * sizeof(uint64_t));
        return neg[4] == 0 && neg[3] == 0 && (neg[2] >> 5) == 0;
    }
    sign = 1;
    memcpy(mag, acc, 4 * sizeof(uint64_t));
    return acc[4] == 0 && acc[3] == 0 && (acc[2] >> 5) == 0;
}

// k (< n) -> k1 + k2*lambda with |k1|,|k2| ~ 2^129; false on any anomaly
static bool glv_split(const Sc& k, int& s1, uint64_t k1[4], int& s2,
                      uint64_t k2[4]) {
    uint64_t c1[4], c2[4];
    _mul_shift384(c1, k.v, GLV.g1);
    _mul_shift384(c2, k.v, GLV.g2);
    // k1 = k - c1*a1 - c2*a2 ; k2 = c1*|b1| - c2*b2   (b2 = a1)
    uint64_t acc1[5] = {k.v[0], k.v[1], k.v[2], k.v[3], 0};
    _acc_submul(acc1, c1, A1);
    _acc_submul(acc1, c2, A2);
    uint64_t acc2[5] = {0, 0, 0, 0, 0};
    _acc_addmul(acc2, c1, B1ABS);
    _acc_submul(acc2, c2, A1);
    return _acc_to_signed(acc1, s1, k1) && _acc_to_signed(acc2, s2, k2);
}

// static wNAF(8) table of odd multiples of G: [1,3,...,127]G, affine.
// Built once at first verify (generic code; ~50us) and reused forever.
static Aff G_TAB[64];
static Aff G_LAM_TAB[64];  // phi applied: (beta*x, y) = odd multiples of [lambda]G

static void build_g_table() {
    Jac G = {GX, GY, {{1, 0, 0, 0}}};
    Jac G2;
    jac_double(G2, G);
    Jac cur = G;
    Jac jtab[64];
    jtab[0] = G;
    for (int i = 1; i < 64; i++) {
        jac_add(cur, cur, G2);
        jtab[i] = cur;
    }
    // batch-normalize to affine (minv.h: one inversion for all 64 Z's)
    Fp* zptr[64];
    Fp zinvs[64];
    for (int i = 0; i < 64; i++) zptr[i] = &jtab[i].Z;
    batch_invert(zptr, zinvs, 64, FP_ONE, fp_mul, fp_invert);
    for (int i = 0; i < 64; i++) {
        Fp zi2, zi3;
        fp_sq(zi2, zinvs[i]);
        fp_mul(zi3, zi2, zinvs[i]);
        fp_mul(G_TAB[i].x, jtab[i].X, zi2);
        fp_mul(G_TAB[i].y, jtab[i].Y, zi3);
        // phi([m]G) = [m*lambda]G = (beta*x, y)
        fp_mul(G_LAM_TAB[i].x, G_TAB[i].x, BETA);
        G_LAM_TAB[i].y = G_TAB[i].y;
    }
}

// jac [k]P by plain double-and-add — startup self-check use only
static void _jac_mul_slow(Jac& o, const uint64_t k[4], const Jac& P) {
    jac_infinity(o);
    for (int bit = 255; bit >= 0; bit--) {
        jac_double(o, o);
        if ((k[bit / 64] >> (bit % 64)) & 1) jac_add(o, o, P);
    }
}

static void init_glv() {
    // lambda order: lambda != 1 and lambda^3 == 1 (mod n)
    Sc lam, l2, l3, one = {{1, 0, 0, 0}};
    memcpy(lam.v, LAMBDA, sizeof LAMBDA);
    sc_mul(l2, lam, lam);
    sc_mul(l3, l2, lam);
    if (sc_cmp_raw(lam.v, one.v) == 0 || sc_cmp_raw(l3.v, one.v) != 0) return;
    // basis rows must satisfy a + b*lambda == 0 (mod n) (b1 negative)
    Sc a1s, b1s, a2s, t;
    memcpy(a1s.v, A1, sizeof A1);
    memcpy(b1s.v, B1ABS, sizeof B1ABS);
    memcpy(a2s.v, A2, sizeof A2);
    sc_mul(t, b1s, lam);  // |b1|*lambda; row1: a1 - |b1|*lambda == 0
    uint64_t chk[4];
    memcpy(chk, a1s.v, sizeof chk);
    {
        u128 borrow = 0;
        for (int i = 0; i < 4; i++) {
            u128 d = (u128)chk[i] - t.v[i] - borrow;
            chk[i] = (uint64_t)d;
            borrow = (d >> 64) ? 1 : 0;
        }
        if (borrow) {  // wrapped: add n back
            u128 carry = 0;
            for (int i = 0; i < 4; i++) {
                u128 s = (u128)chk[i] + N[i] + carry;
                chk[i] = (uint64_t)s;
                carry = (uint64_t)(s >> 64);
            }
        }
    }
    if (chk[0] | chk[1] | chk[2] | chk[3]) return;
    // rounded quotients by long division — no transcribed constants
    _div_round_shift384(GLV.g1, A1);      // g1 from b2 (= a1)
    _div_round_shift384(GLV.g2, B1ABS);   // g2 from |b1|
    // split self-test: k1 + k2*lambda == k (mod n) over a scalar sweep
    uint64_t seed = 0x243F6A8885A308D3ull;  // pi digits, arbitrary
    for (int trial = 0; trial < 64; trial++) {
        Sc k;
        if (trial == 0)
            memset(k.v, 0, sizeof k.v);
        else if (trial == 1) {
            memcpy(k.v, N, sizeof k.v);
            k.v[0] -= 1;  // n - 1
        } else
            for (int i = 0; i < 4; i++) {
                seed = seed * 6364136223846793005ull + 1442695040888963407ull;
                k.v[i] = seed;
            }
        while (sc_cmp_raw(k.v, N) >= 0) sc_sub_n(k.v);
        int s1, s2;
        uint64_t k1[4], k2[4];
        if (!glv_split(k, s1, k1, s2, k2)) return;
        Sc k1s, k2s, rec;
        memcpy(k1s.v, k1, sizeof k1);
        memcpy(k2s.v, k2, sizeof k2);
        while (sc_cmp_raw(k1s.v, N) >= 0) sc_sub_n(k1s.v);
        while (sc_cmp_raw(k2s.v, N) >= 0) sc_sub_n(k2s.v);
        auto negate_mod_n = [](Sc& x) {
            if (sc_iszero(x)) return;
            uint64_t neg[4];
            memcpy(neg, N, sizeof neg);
            u128 borrow = 0;
            for (int i = 0; i < 4; i++) {
                u128 d = (u128)neg[i] - x.v[i] - borrow;
                neg[i] = (uint64_t)d;
                borrow = (d >> 64) ? 1 : 0;
            }
            memcpy(x.v, neg, sizeof neg);
        };
        if (s1 < 0) negate_mod_n(k1s);
        if (s2 < 0) negate_mod_n(k2s);
        sc_mul(rec, k2s, lam);
        u128 carry = 0;
        for (int i = 0; i < 4; i++) {
            u128 s = (u128)rec.v[i] + k1s.v[i] + carry;
            rec.v[i] = (uint64_t)s;
            carry = (uint64_t)(s >> 64);
        }
        if (carry) {
            u128 c2 = 0;
            uint64_t add[4] = {NC[0], NC[1], NC[2], 0};
            for (int i = 0; i < 4; i++) {
                u128 s = (u128)rec.v[i] + add[i] + c2;
                rec.v[i] = (uint64_t)s;
                c2 = (uint64_t)(s >> 64);
            }
        }
        while (sc_cmp_raw(rec.v, N) >= 0) sc_sub_n(rec.v);
        if (sc_cmp_raw(rec.v, k.v) != 0) return;
    }
    // geometric check: phi(G) = (beta*Gx, Gy) must equal [lambda]G
    Jac G = {GX, GY, {{1, 0, 0, 0}}}, lamG;
    _jac_mul_slow(lamG, LAMBDA, G);
    Fp zinv, zi2, zi3, xa, ya, bx;
    fp_invert(zinv, lamG.Z);
    fp_sq(zi2, zinv);
    fp_mul(zi3, zi2, zinv);
    fp_mul(xa, lamG.X, zi2);
    fp_mul(ya, lamG.Y, zi3);
    fp_mul(bx, GX, BETA);
    if (memcmp(xa.v, bx.v, sizeof xa.v) != 0 ||
        memcmp(ya.v, GY.v, sizeof ya.v) != 0)
        return;
    GLV.ok = true;
}

static void ensure_g_table() {
    // C++11 magic static: thread-safe one-time init (the batch entry
    // point fans verifies out across a thread pool)
    static const bool ready = (build_g_table(), init_glv(), true);
    (void)ready;
}

// decompress 33-byte SEC1 pubkey
static bool point_decompress(Jac& o, const uint8_t in[33]) {
    if (in[0] != 0x02 && in[0] != 0x03) return false;
    Fp x;
    fp_frombytes_be(x, in + 1);
    // reject x >= p
    uint8_t canon[32];
    fp_tobytes_be(canon, x);
    if (memcmp(canon, in + 1, 32) != 0) return false;
    Fp rhs, y;
    fp_sq(rhs, x);
    fp_mul(rhs, rhs, x);
    fp_add(rhs, rhs, FP_B);  // x^3 + 7
    if (!fp_sqrt(y, rhs)) return false;
    // choose parity
    if ((y.v[0] & 1) != (in[0] & 1)) {
        Fp py = {{P[0], P[1], P[2], P[3]}};
        fp_sub(y, py, y);
    }
    o.X = x;
    o.Y = y;
    memset(&o.Z, 0, sizeof o.Z);
    o.Z.v[0] = 1;
    return true;
}

// introspection: did the GLV constants validate at startup? (tests pin
// this so a silent fallback to the 2-stream path can't masquerade as the
// optimized configuration)
extern "C" int tm_secp256k1_glv_active(void) {
    ensure_g_table();
    return GLV.ok ? 1 : 0;
}

// ---------------------------------------------------------- verify plumbing

struct SigPre {
    Sc r, s, z;  // signature scalars + message digest mod n
};

// per-pubkey decompression cache shared by the single-shot and batched
// entries: a stable validator set pays the sqrt once per key, not once
// per signature
static ShardedPubCache<33, 64> q_cache;

// per-pubkey AFFINE wNAF table cache (8 odd multiples, 512 B/key): in
// steady state the same validator keys verify every height, so the
// table build AND its share of the batch normalization disappear on a
// hit — and even the single-shot path gets all-affine streams. Filled
// only by the batched core (affine tables come ~free there, from the
// shared inversion); 1024 entries/shard x 16 shards = 8 MB cap.
static_assert(sizeof(Aff[8]) == 512, "qtab cache value layout");
static ShardedPubCache<33, 8 * sizeof(Aff)> qtab_cache(1024);

// parse + range checks + message digest; false => definitively invalid
// (zero/overflowing r or s, high-S). Pubkey decompression happens
// LAZILY via fetch_q — a per-key table-cache hit implies a valid pubkey
// and never needs the decompressed point at all.
static bool sig_parse(const uint8_t* msg, size_t msglen,
                      const uint8_t sig[64], SigPre& o) {
    uint64_t rraw[4], sraw[4];
    for (int i = 0; i < 4; i++) {
        rraw[3 - i] = 0;
        sraw[3 - i] = 0;
        for (int j = 0; j < 8; j++) {
            rraw[3 - i] = (rraw[3 - i] << 8) | sig[8 * i + j];
            sraw[3 - i] = (sraw[3 - i] << 8) | sig[32 + 8 * i + j];
        }
    }
    memcpy(o.r.v, rraw, sizeof rraw);
    memcpy(o.s.v, sraw, sizeof sraw);
    if (sc_iszero(o.r) || sc_iszero(o.s)) return false;
    if (sc_cmp_raw(rraw, N) >= 0) return false;
    if (sc_cmp_raw(sraw, N) >= 0) return false;
    if (sc_cmp_raw(sraw, NHALF) > 0) return false;  // high-S malleability

    uint8_t digest[32];
    sha256(msg, msglen, digest);
    sc_frombytes_be(o.z, digest);
    return true;
}

// decompressed pubkey (Z = 1) via the per-key cache; false on a bad key
static bool fetch_q(const uint8_t pub[33], Jac& Q) {
    uint8_t q_b[64];
    if (!q_cache.get(pub, q_b, [](const uint8_t* k, uint8_t* v) {
            Jac P0;
            if (!point_decompress(P0, k)) return false;
            fp_tobytes_be(v, P0.X);  // Z = 1 at decompression
            fp_tobytes_be(v + 32, P0.Y);
            return true;
        }))
        return false;
    fp_frombytes_be(Q.X, q_b);
    fp_frombytes_be(Q.Y, q_b + 32);
    memset(&Q.Z, 0, sizeof Q.Z);
    Q.Z.v[0] = 1;
    return true;
}

// add tab[|d|/2] (or its negation) into R; overloads keep the Strauss
// loop below generic over the table representation
static void tab_apply(Jac& R, const Aff* tab, int d) {
    if (d > 0) {
        jac_madd(R, R, tab[(d - 1) >> 1]);
    } else if (d < 0) {
        Aff neg = tab[(-d - 1) >> 1];
        Fp py = {{P[0], P[1], P[2], P[3]}};
        fp_sub(neg.y, py, neg.y);
        jac_madd(R, R, neg);
    }
}

static void tab_apply(Jac& R, const Jac* tab, int d) {
    if (d > 0) {
        jac_add(R, R, tab[(d - 1) >> 1]);
    } else if (d < 0) {
        Jac neg = tab[(-d - 1) >> 1];
        Fp py = {{P[0], P[1], P[2], P[3]}};
        fp_sub(neg.Y, py, neg.Y);
        jac_add(R, R, neg);
    }
}

// phi table: [m*lambda]Q = (beta*x, y) applied entrywise
static void phi_tab(Aff o[8], const Aff in[8]) {
    for (int i = 0; i < 8; i++) {
        fp_mul(o[i].x, in[i].x, BETA);
        o[i].y = in[i].y;
    }
}

static void phi_tab(Jac o[8], const Jac in[8]) {
    for (int i = 0; i < 8; i++) {
        fp_mul(o[i].X, in[i].X, BETA);
        o[i].Y = in[i].Y;
        o[i].Z = in[i].Z;
    }
}

// R = [u1]G + [u2]Q — the interleaved Strauss/GLV multiplication, generic
// over the per-key table representation: Jacobian for the single-shot
// path (building it needs no inversion), affine for the batched path
// (one shared inversion normalizes every table, so the two Q streams use
// mixed adds, 8M+3S, instead of the general add's 12M+4S). Returns false
// only when every stream is zero (u1 = u2 = 0 — never a valid signature).
template <typename PT>
static bool strauss_double_mul(Jac& R, const Sc& u1, const Sc& u2,
                               const PT q_tab[8]) {
    int s1a = 1, s1b = 1, s2a = 1, s2b = 1;
    uint64_t u1a[4], u1b[4], u2a[4], u2b[4];
    bool use_glv = GLV.ok && glv_split(u1, s1a, u1a, s1b, u1b) &&
                   glv_split(u2, s2a, u2a, s2b, u2b);
    if (use_glv) {
        PT ql_tab[8];
        phi_tab(ql_tab, q_tab);
        int8_t n1a[257], n1b[257], n2a[257], n2b[257];
        Sc t;
        memcpy(t.v, u1a, sizeof u1a);
        int la = wnaf(n1a, t, 8);
        memcpy(t.v, u1b, sizeof u1b);
        int lb = wnaf(n1b, t, 8);
        memcpy(t.v, u2a, sizeof u2a);
        int lc = wnaf(n2a, t, 5);
        memcpy(t.v, u2b, sizeof u2b);
        int ld = wnaf(n2b, t, 5);
        int top = la;
        if (lb > top) top = lb;
        if (lc > top) top = lc;
        if (ld > top) top = ld;
        top -= 1;
        if (top < 0) return false;
        jac_infinity(R);
        for (int i = top; i >= 0; i--) {
            jac_double(R, R);
            tab_apply(R, G_TAB, s1a * n1a[i]);
            tab_apply(R, G_LAM_TAB, s1b * n1b[i]);
            tab_apply(R, q_tab, s2a * n2a[i]);
            tab_apply(R, ql_tab, s2b * n2b[i]);
        }
    } else {
        // 2-stream Strauss fallback: one shared 256-bit doubling chain
        int8_t n1[257], n2[257];
        int l1 = wnaf(n1, u1, 8);
        int l2 = wnaf(n2, u2, 5);
        int top = (l1 > l2 ? l1 : l2) - 1;
        if (top < 0) return false;  // u1 = u2 = 0 cannot yield x(R) = r != 0
        jac_infinity(R);
        for (int i = top; i >= 0; i--) {
            jac_double(R, R);
            tab_apply(R, G_TAB, n1[i]);
            tab_apply(R, q_tab, n2[i]);
        }
    }
    return true;
}

// r' == R.x (affine) mod n, compared in Jacobian coordinates: check
// X == cand * Z^2 for cand in {r, r+n} (no field inversion). r < n
// so r+n < 2n < 2^257; the r+n candidate only exists when r+n < p.
static int rx_matches(const Jac& R, const Sc& r) {
    Fp z2;
    fp_sq(z2, R.Z);
    for (int cand = 0; cand < 2; cand++) {
        uint64_t c[5] = {r.v[0], r.v[1], r.v[2], r.v[3], 0};
        if (cand == 1) {
            u128 carry = 0;
            for (int i = 0; i < 4; i++) {
                u128 s2 = (u128)c[i] + N[i] + carry;
                c[i] = (uint64_t)s2;
                carry = (uint64_t)(s2 >> 64);
            }
            c[4] = (uint64_t)carry;
            // candidate must be a canonical field element: r + n < p
            if (c[4] || fp_cmp_raw(c, P) >= 0) break;
        }
        Fp cf = {{c[0], c[1], c[2], c[3]}};
        Fp t;
        fp_mul(t, cf, z2);
        if (memcmp(t.v, R.X.v, sizeof t.v) == 0) return 1;
    }
    return 0;
}

// per-key wNAF(5) table of odd multiples [1,3,...,15]Q, Jacobian
static void build_q_tab(Jac q_tab[8], const Jac& Q) {
    Jac Q2;
    jac_double(Q2, Q);
    q_tab[0] = Q;
    for (int i = 1; i < 8; i++) jac_add(q_tab[i], q_tab[i - 1], Q2);
}

// public entry: tendermint wire format — 33B compressed pubkey, 64B r||s,
// low-S enforced; msg is hashed with SHA-256. Returns 1 valid / 0 invalid.
extern "C" int tm_secp256k1_verify(const uint8_t pub[33], const uint8_t* msg,
                                   size_t msglen, const uint8_t sig[64]) {
    SigPre p;
    if (!sig_parse(msg, msglen, sig, p)) return 0;

    Sc w, u1, u2;
    sc_invert(w, p.s);
    sc_mul(u1, p.z, w);
    sc_mul(u2, p.r, w);

    ensure_g_table();
    Jac R;
    Aff qa[8];
    if (qtab_cache.lookup(pub, reinterpret_cast<uint8_t*>(qa))) {
        // steady-state key: cached affine table, all four streams mixed
        // adds — the decompressed point is never even fetched
        if (!strauss_double_mul(R, u1, u2, qa)) return 0;
    } else {
        // Jacobian per-key table: a one-off normalization to affine
        // would cost a field inversion — for ONE signature the general
        // adds it saves are cheaper than that (the batched path below
        // amortizes the inversion across a whole sub-chunk, gets the
        // affine tables ~free, and populates the cache above)
        Jac Q;
        if (!fetch_q(pub, Q)) return 0;
        Jac q_tab[8];
        build_q_tab(q_tab, Q);
        if (!strauss_double_mul(R, u1, u2, q_tab)) return 0;
    }
    if (jac_is_infinity(R)) return 0;
    return rx_matches(R, p.r);
}

// Batched core — the native backend's fast path (batch.cpp shards [lo,hi)
// ranges of the batch across threads; each range is processed here in
// 64-signature sub-chunks). Two Montgomery-trick amortizations per
// sub-chunk, each replacing per-signature work that dominates the
// single-shot profile:
//   1. s^-1 mod n: one Fermat ladder (~256 squarings) for the whole
//      sub-chunk instead of one per signature;
//   2. per-key wNAF tables normalized to affine with ONE field inversion,
//      so the two pubkey streams of the Strauss/GLV loop use mixed adds
//      (8M+3S) instead of general Jacobian adds (12M+4S).
// Per-signature verdicts are bit-identical to tm_secp256k1_verify: the
// same parse/reject set, the same strict low-S rule, the same final
// Jacobian x-compare — only shared-subexpression scheduling differs.
extern "C" void tm_secp256k1_verify_range(const uint8_t* pubs,
                                          const uint8_t* msgs,
                                          const uint64_t* offsets,
                                          const uint8_t* sigs, size_t lo,
                                          size_t hi, uint8_t* out) {
    ensure_g_table();
    constexpr size_t CH = 64;
    SigPre pre[CH];
    Sc w[CH];
    Jac qt[CH][8];
    Aff qa[CH][8];
    Fp zinvs[CH * 8];
    bool valid[CH];
    for (size_t base = lo; base < hi; base += CH) {
        const size_t m = (hi - base < CH) ? (hi - base) : CH;
        for (size_t i = 0; i < m; i++) {
            const size_t g = base + i;
            valid[i] = sig_parse(msgs + offsets[g],
                                 (size_t)(offsets[g + 1] - offsets[g]),
                                 sigs + 64 * g, pre[i]);
        }
        // ---- batch inversion of every s mod n (minv.h)
        {
            Sc* sptr[CH];
            Sc winv[CH];
            size_t nv = 0;
            for (size_t i = 0; i < m; i++)
                if (valid[i]) sptr[nv++] = &pre[i].s;
            static const Sc SC_ONE = {{1, 0, 0, 0}};
            batch_invert(sptr, winv, nv, SC_ONE, sc_mul, sc_invert);
            nv = 0;
            for (size_t i = 0; i < m; i++)
                if (valid[i]) w[i] = winv[nv++];
        }
        // ---- per-key tables: cached affine where the key was seen
        // before, else built Jacobian and batch-normalized to affine
        bool tab_hit[CH];
        for (size_t i = 0; i < m; i++) {
            if (!valid[i]) continue;
            tab_hit[i] = qtab_cache.lookup(
                pubs + 33 * (base + i), reinterpret_cast<uint8_t*>(qa[i]));
            if (tab_hit[i]) continue;
            Jac Q;  // lazy: only missed keys decompress
            if (!fetch_q(pubs + 33 * (base + i), Q)) {
                valid[i] = false;
                continue;
            }
            build_q_tab(qt[i], Q);
            // a prime-order group has no small-order points, so no table
            // entry can be infinity; guard anyway — a zero Z would poison
            // the shared inversion chain below
            for (int j = 0; j < 8; j++)
                if (fp_iszero(qt[i][j].Z)) {
                    valid[i] = false;
                    break;
                }
        }
        size_t nz = 0;
        Fp* zptr[CH * 8];
        for (size_t i = 0; i < m; i++) {
            if (!valid[i] || tab_hit[i]) continue;
            for (int j = 0; j < 8; j++) zptr[nz++] = &qt[i][j].Z;
        }
        batch_invert(zptr, zinvs, nz, FP_ONE, fp_mul, fp_invert);
        nz = 0;
        for (size_t i = 0; i < m; i++) {
            if (!valid[i] || tab_hit[i]) continue;
            for (int j = 0; j < 8; j++) {
                Fp zi2, zi3;
                fp_sq(zi2, zinvs[nz]);
                fp_mul(zi3, zi2, zinvs[nz]);
                nz++;
                fp_mul(qa[i][j].x, qt[i][j].X, zi2);
                fp_mul(qa[i][j].y, qt[i][j].Y, zi3);
            }
            qtab_cache.put(pubs + 33 * (base + i),
                           reinterpret_cast<const uint8_t*>(qa[i]));
        }
        // ---- main loops (all four streams on affine tables)
        for (size_t i = 0; i < m; i++) {
            if (!valid[i]) {
                out[base + i] = 0;
                continue;
            }
            Sc u1, u2;
            sc_mul(u1, pre[i].z, w[i]);
            sc_mul(u2, pre[i].r, w[i]);
            Jac R;
            int okv = 0;
            if (strauss_double_mul(R, u1, u2, qa[i]) && !jac_is_infinity(R))
                okv = rx_matches(R, pre[i].r);
            out[base + i] = (uint8_t)okv;
        }
    }
}

}  // namespace tmnative
