// secp256k1 ECDSA verification, clean-room C++.
//
// The native-parity replacement for the reference's vendored libsecp256k1
// (crypto/secp256k1/internal, 17.5k LoC of C): this framework only needs
// the verify path natively (signing stays in the Python key objects), in
// tendermint's wire format — 33-byte compressed pubkey, 64-byte r||s
// signature with the low-S rule (reference secp256k1_nocgo.go:40-50),
// SHA-256 message digest.
//
// Field arithmetic: 4x64 limbs, reduction by p = 2^256 - 0x1000003D1.
// Scalar arithmetic mod n: folding reduction by c = 2^256 - n (129 bits).
// Points: Jacobian coordinates; verification runs one interleaved
// Strauss double-scalar multiplication (wNAF(8) over a static affine
// G table + wNAF(5) over a per-key Jacobian table) and compares R.x
// against r in Jacobian coordinates, so the whole verify needs no field
// inversion. Measured 2.6x the OpenSSL generic-EC path this backs up
// (OpenSSL has no specialized secp256k1 code) on one core — README's
// round-4 native-core table has the numbers.
#include <cstdint>
#include <cstring>
#include "sha2.h"
#include "wnaf.h"

namespace tmnative {

typedef unsigned __int128 u128;

// ------------------------------------------------------------- field (mod p)

struct Fp {
    uint64_t v[4];  // little-endian limbs
};

static const uint64_t P[4] = {0xFFFFFFFEFFFFFC2Full, 0xFFFFFFFFFFFFFFFFull,
                              0xFFFFFFFFFFFFFFFFull, 0xFFFFFFFFFFFFFFFFull};
static const uint64_t PC = 0x1000003D1ull;  // 2^256 mod p

static int fp_cmp_raw(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void fp_sub_p(uint64_t a[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a[i] - P[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static void fp_norm(Fp& a) {
    if (fp_cmp_raw(a.v, P) >= 0) fp_sub_p(a.v);
}

static void fp_add(Fp& o, const Fp& a, const Fp& b) {
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 s = (u128)a.v[i] + b.v[i] + carry;
        o.v[i] = (uint64_t)s;
        carry = (uint64_t)(s >> 64);
    }
    if (carry) {  // wrapped 2^256: add PC
        u128 c = PC;
        for (int i = 0; i < 4 && c; i++) {
            u128 s = (u128)o.v[i] + c;
            o.v[i] = (uint64_t)s;
            c = (uint64_t)(s >> 64);
        }
    }
    fp_norm(o);
}

static void fp_sub(Fp& o, const Fp& a, const Fp& b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a.v[i] - b.v[i] - borrow;
        o.v[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    if (borrow) {  // add p back
        u128 carry = 0;
        for (int i = 0; i < 4; i++) {
            u128 s = (u128)o.v[i] + P[i] + carry;
            o.v[i] = (uint64_t)s;
            carry = (uint64_t)(s >> 64);
        }
    }
}

static void fp_mul(Fp& o, const Fp& a, const Fp& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a.v[i] * b.v[j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        t[i + 4] += (uint64_t)carry;
    }
    // fold: value = lo + hi * 2^256 ≡ lo + hi * PC (twice)
    uint64_t r[5] = {t[0], t[1], t[2], t[3], 0};
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 cur = (u128)r[i] + (u128)t[4 + i] * PC + carry;
        r[i] = (uint64_t)cur;
        carry = (uint64_t)(cur >> 64);
    }
    r[4] = (uint64_t)carry;
    // second fold of the (small) top limb
    u128 c2 = (u128)r[4] * PC;
    uint64_t res[4] = {r[0], r[1], r[2], r[3]};
    for (int i = 0; i < 4 && c2; i++) {
        u128 s = (u128)res[i] + (uint64_t)c2;
        res[i] = (uint64_t)s;
        c2 = (c2 >> 64) + (s >> 64);
    }
    memcpy(o.v, res, sizeof res);
    fp_norm(o);
}

static void fp_sq(Fp& o, const Fp& a) { fp_mul(o, a, a); }

static void fp_pow(Fp& o, const Fp& a, const uint64_t e[4]) {
    Fp result = {{1, 0, 0, 0}}, base = a;
    for (int i = 0; i < 256; i++) {
        if ((e[i / 64] >> (i % 64)) & 1) fp_mul(result, result, base);
        fp_sq(base, base);
    }
    o = result;
}

static void fp_invert(Fp& o, const Fp& a) {
    uint64_t e[4];
    memcpy(e, P, sizeof e);
    e[0] -= 2;  // p - 2 (no borrow: low limb ends ...C2F)
    fp_pow(o, a, e);
}

static bool fp_sqrt(Fp& o, const Fp& a) {  // p ≡ 3 (mod 4)
    uint64_t e[4];
    memcpy(e, P, sizeof e);
    // (p+1)/4: add 1 then shift right 2
    e[0] += 1;
    for (int i = 0; i < 4; i++) {
        e[i] >>= 2;
        if (i < 3) e[i] |= e[i + 1] << 62;
    }
    fp_pow(o, a, e);
    Fp chk;
    fp_sq(chk, o);
    return memcmp(chk.v, a.v, sizeof chk.v) == 0;
}

static bool fp_iszero(const Fp& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static void fp_frombytes_be(Fp& o, const uint8_t in[32]) {
    for (int i = 0; i < 4; i++) {
        o.v[3 - i] = 0;
        for (int j = 0; j < 8; j++) o.v[3 - i] = (o.v[3 - i] << 8) | in[8 * i + j];
    }
}

static void fp_tobytes_be(uint8_t out[32], const Fp& a) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = uint8_t(a.v[3 - i] >> (56 - 8 * j));
}

// ------------------------------------------------------------ scalars (mod n)

static const uint64_t N[4] = {0xBFD25E8CD0364141ull, 0xBAAEDCE6AF48A03Bull,
                              0xFFFFFFFFFFFFFFFEull, 0xFFFFFFFFFFFFFFFFull};
// n/2 for the low-S rule
static const uint64_t NHALF[4] = {0xDFE92F46681B20A0ull, 0x5D576E7357A4501Dull,
                                  0xFFFFFFFFFFFFFFFFull, 0x7FFFFFFFFFFFFFFFull};

struct Sc {
    uint64_t v[4];
};

static int sc_cmp_raw(const uint64_t a[4], const uint64_t b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static void sc_sub_n(uint64_t a[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a[i] - N[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static bool sc_iszero(const Sc& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static void sc_frombytes_be(Sc& o, const uint8_t in[32]) {
    for (int i = 0; i < 4; i++) {
        o.v[3 - i] = 0;
        for (int j = 0; j < 8; j++) o.v[3 - i] = (o.v[3 - i] << 8) | in[8 * i + j];
    }
    while (sc_cmp_raw(o.v, N) >= 0) sc_sub_n(o.v);
}

// c = 2^256 - n = 0x1_45512319_50B75FC4_402DA173_2FC9BEBF (129 bits):
// value mod n folds as lo + hi*c, shrinking ~127 bits per fold.
static const uint64_t NC[3] = {0x402DA1732FC9BEBFull, 0x4551231950B75FC4ull,
                               1ull};

// reduce an 8-limb (512-bit) value mod n into o
static void sc_reduce_wide(Sc& o, const uint64_t t[8]) {
    // working value: up to 7 limbs across folds
    uint64_t v[8];
    memcpy(v, t, sizeof v);
    int top = 8;  // limbs in use
    while (top > 4) {
        int hi_limbs = top - 4;
        uint64_t hi[4] = {0, 0, 0, 0};
        memcpy(hi, v + 4, hi_limbs * sizeof(uint64_t));
        // v = v[0..3] + hi * c   (hi*c has at most hi_limbs+3 limbs)
        uint64_t acc[8] = {v[0], v[1], v[2], v[3], 0, 0, 0, 0};
        for (int i = 0; i < hi_limbs; i++) {
            u128 carry = 0;
            for (int j = 0; j < 3; j++) {
                u128 cur = (u128)acc[i + j] + (u128)hi[i] * NC[j] + carry;
                acc[i + j] = (uint64_t)cur;
                carry = (uint64_t)(cur >> 64);
            }
            int k = i + 3;
            while (carry) {
                u128 cur = (u128)acc[k] + carry;
                acc[k] = (uint64_t)cur;
                carry = (uint64_t)(cur >> 64);
                k++;
            }
        }
        memcpy(v, acc, sizeof v);
        top = 8;
        while (top > 4 && v[top - 1] == 0) top--;
    }
    while (sc_cmp_raw(v, N) >= 0) sc_sub_n(v);
    memcpy(o.v, v, 4 * sizeof(uint64_t));
}

// o = a*b mod n — 512-bit schoolbook product + folding reduction
static void sc_mul(Sc& o, const Sc& a, const Sc& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a.v[i] * b.v[j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        t[i + 4] += (uint64_t)carry;
    }
    sc_reduce_wide(o, t);
}

static void sc_invert(Sc& o, const Sc& a) {  // Fermat: a^(n-2), 4-bit windows
    Sc table[16];  // table[i] = a^i (i >= 1)
    table[1] = a;
    for (int i = 2; i < 16; i++) sc_mul(table[i], table[i - 1], a);
    uint64_t e[4];
    memcpy(e, N, sizeof e);
    e[0] -= 2;
    Sc result = {{1, 0, 0, 0}};
    bool started = false;
    for (int nib = 63; nib >= 0; nib--) {
        if (started)
            for (int d = 0; d < 4; d++) sc_mul(result, result, result);
        int idx = (e[nib / 16] >> (4 * (nib % 16))) & 0xF;
        if (idx) {
            if (started)
                sc_mul(result, result, table[idx]);
            else {
                result = table[idx];
                started = true;
            }
        }
    }
    o = result;
}

// --------------------------------------------------------------- points

struct Jac {  // Jacobian: x = X/Z^2, y = Y/Z^3; Z = 0 => infinity
    Fp X, Y, Z;
};

static const Fp FP_B = {{7, 0, 0, 0}};
static const Fp GX = {{0x59F2815B16F81798ull, 0x029BFCDB2DCE28D9ull,
                       0x55A06295CE870B07ull, 0x79BE667EF9DCBBACull}};
static const Fp GY = {{0x9C47D08FFB10D4B8ull, 0xFD17B448A6855419ull,
                       0x5DA4FBFC0E1108A8ull, 0x483ADA7726A3C465ull}};

static void jac_infinity(Jac& o) {
    memset(&o, 0, sizeof o);
    o.X.v[0] = 1;
    o.Y.v[0] = 1;
}

static bool jac_is_infinity(const Jac& p) { return fp_iszero(p.Z); }

static void jac_double(Jac& o, const Jac& p) {
    if (jac_is_infinity(p) || fp_iszero(p.Y)) {
        jac_infinity(o);
        return;
    }
    Fp A, B, C, D, X3, Y3, Z3, t;
    fp_sq(A, p.X);                       // A = X^2
    fp_sq(B, p.Y);                       // B = Y^2
    fp_sq(C, B);                         // C = B^2
    // D = 2((X+B)^2 - A - C)
    fp_add(t, p.X, B);
    fp_sq(t, t);
    fp_sub(t, t, A);
    fp_sub(t, t, C);
    fp_add(D, t, t);
    Fp E, F;
    fp_add(E, A, A);
    fp_add(E, E, A);                     // E = 3A (a = 0 curve)
    fp_sq(F, E);                         // F = E^2
    fp_sub(X3, F, D);
    fp_sub(X3, X3, D);                   // X3 = F - 2D
    fp_sub(t, D, X3);
    fp_mul(t, E, t);
    Fp C8;
    fp_add(C8, C, C);
    fp_add(C8, C8, C8);
    fp_add(C8, C8, C8);                  // 8C
    fp_sub(Y3, t, C8);                   // Y3 = E(D - X3) - 8C
    fp_mul(Z3, p.Y, p.Z);
    fp_add(Z3, Z3, Z3);                  // Z3 = 2 Y Z
    o.X = X3; o.Y = Y3; o.Z = Z3;
}

static void jac_add(Jac& o, const Jac& p, const Jac& q) {
    if (jac_is_infinity(p)) { o = q; return; }
    if (jac_is_infinity(q)) { o = p; return; }
    Fp Z1Z1, Z2Z2, U1, U2, S1, S2, t;
    fp_sq(Z1Z1, p.Z);
    fp_sq(Z2Z2, q.Z);
    fp_mul(U1, p.X, Z2Z2);
    fp_mul(U2, q.X, Z1Z1);
    fp_mul(t, q.Z, Z2Z2);
    fp_mul(S1, p.Y, t);
    fp_mul(t, p.Z, Z1Z1);
    fp_mul(S2, q.Y, t);
    Fp H, R;
    fp_sub(H, U2, U1);
    fp_sub(R, S2, S1);
    if (fp_iszero(H)) {
        if (fp_iszero(R)) { jac_double(o, p); return; }
        jac_infinity(o);  // P + (-P)
        return;
    }
    Fp H2, H3, U1H2, X3, Y3, Z3;
    fp_sq(H2, H);
    fp_mul(H3, H2, H);
    fp_mul(U1H2, U1, H2);
    fp_sq(X3, R);
    fp_sub(X3, X3, H3);
    fp_sub(X3, X3, U1H2);
    fp_sub(X3, X3, U1H2);                // X3 = R^2 - H^3 - 2 U1 H^2
    fp_sub(t, U1H2, X3);
    fp_mul(t, R, t);
    Fp S1H3;
    fp_mul(S1H3, S1, H3);
    fp_sub(Y3, t, S1H3);                 // Y3 = R(U1 H^2 - X3) - S1 H^3
    fp_mul(Z3, p.Z, q.Z);
    fp_mul(Z3, Z3, H);                   // Z3 = Z1 Z2 H
    o.X = X3; o.Y = Y3; o.Z = Z3;
}

struct Aff {  // affine point (never infinity in the tables below)
    Fp x, y;
};

// mixed addition: o = p + q with q affine (8 mul + 3 sq vs jac_add's 12+4)
static void jac_madd(Jac& o, const Jac& p, const Aff& q) {
    if (jac_is_infinity(p)) {
        o.X = q.x;
        o.Y = q.y;
        memset(&o.Z, 0, sizeof o.Z);
        o.Z.v[0] = 1;
        return;
    }
    Fp Z1Z1, U2, S2, t;
    fp_sq(Z1Z1, p.Z);
    fp_mul(U2, q.x, Z1Z1);
    fp_mul(t, p.Z, Z1Z1);
    fp_mul(S2, q.y, t);
    Fp H, R;
    fp_sub(H, U2, p.X);
    fp_sub(R, S2, p.Y);
    if (fp_iszero(H)) {
        if (fp_iszero(R)) {
            jac_double(o, p);
            return;
        }
        jac_infinity(o);
        return;
    }
    Fp H2, H3, V, X3, Y3, Z3;
    fp_sq(H2, H);
    fp_mul(H3, H2, H);
    fp_mul(V, p.X, H2);
    fp_sq(X3, R);
    fp_sub(X3, X3, H3);
    fp_sub(X3, X3, V);
    fp_sub(X3, X3, V);                  // X3 = R^2 - H^3 - 2V
    fp_sub(t, V, X3);
    fp_mul(t, R, t);
    Fp YH3;
    fp_mul(YH3, p.Y, H3);
    fp_sub(Y3, t, YH3);                 // Y3 = R(V - X3) - Y1 H^3
    fp_mul(Z3, p.Z, H);                 // Z3 = Z1 H
    o.X = X3; o.Y = Y3; o.Z = Z3;
}

static int wnaf(int8_t out[257], const Sc& k, int w) {
    return wnaf_digits(out, k.v, w);
}

// static wNAF(8) table of odd multiples of G: [1,3,...,127]G, affine.
// Built once at first verify (generic code; ~50us) and reused forever.
static Aff G_TAB[64];

static void build_g_table() {
    Jac G = {GX, GY, {{1, 0, 0, 0}}};
    Jac G2;
    jac_double(G2, G);
    Jac cur = G;
    Jac jtab[64];
    jtab[0] = G;
    for (int i = 1; i < 64; i++) {
        jac_add(cur, cur, G2);
        jtab[i] = cur;
    }
    // batch-normalize to affine (Montgomery trick: one inversion)
    Fp prods[64], acc = {{1, 0, 0, 0}};
    for (int i = 0; i < 64; i++) {
        prods[i] = acc;                     // prod of Z[0..i-1]
        fp_mul(acc, acc, jtab[i].Z);
    }
    Fp inv;
    fp_invert(inv, acc);
    for (int i = 63; i >= 0; i--) {
        Fp zinv;
        fp_mul(zinv, inv, prods[i]);        // 1/Z[i]
        fp_mul(inv, inv, jtab[i].Z);        // strip Z[i] from the chain
        Fp zi2, zi3;
        fp_sq(zi2, zinv);
        fp_mul(zi3, zi2, zinv);
        fp_mul(G_TAB[i].x, jtab[i].X, zi2);
        fp_mul(G_TAB[i].y, jtab[i].Y, zi3);
    }
}

static void ensure_g_table() {
    // C++11 magic static: thread-safe one-time init (the batch entry
    // point fans verifies out across a thread pool)
    static const bool ready = (build_g_table(), true);
    (void)ready;
}

// decompress 33-byte SEC1 pubkey
static bool point_decompress(Jac& o, const uint8_t in[33]) {
    if (in[0] != 0x02 && in[0] != 0x03) return false;
    Fp x;
    fp_frombytes_be(x, in + 1);
    // reject x >= p
    uint8_t canon[32];
    fp_tobytes_be(canon, x);
    if (memcmp(canon, in + 1, 32) != 0) return false;
    Fp rhs, y;
    fp_sq(rhs, x);
    fp_mul(rhs, rhs, x);
    fp_add(rhs, rhs, FP_B);  // x^3 + 7
    if (!fp_sqrt(y, rhs)) return false;
    // choose parity
    if ((y.v[0] & 1) != (in[0] & 1)) {
        Fp py = {{P[0], P[1], P[2], P[3]}};
        fp_sub(y, py, y);
    }
    o.X = x;
    o.Y = y;
    memset(&o.Z, 0, sizeof o.Z);
    o.Z.v[0] = 1;
    return true;
}

// public entry: tendermint wire format — 33B compressed pubkey, 64B r||s,
// low-S enforced; msg is hashed with SHA-256. Returns 1 valid / 0 invalid.
extern "C" int tm_secp256k1_verify(const uint8_t pub[33], const uint8_t* msg,
                                   size_t msglen, const uint8_t sig[64]) {
    // parse r, s
    uint64_t rraw[4], sraw[4];
    for (int i = 0; i < 4; i++) {
        rraw[3 - i] = 0;
        sraw[3 - i] = 0;
        for (int j = 0; j < 8; j++) {
            rraw[3 - i] = (rraw[3 - i] << 8) | sig[8 * i + j];
            sraw[3 - i] = (sraw[3 - i] << 8) | sig[32 + 8 * i + j];
        }
    }
    Sc r, s;
    memcpy(r.v, rraw, sizeof rraw);
    memcpy(s.v, sraw, sizeof sraw);
    if (sc_iszero(r) || sc_iszero(s)) return 0;
    if (sc_cmp_raw(rraw, N) >= 0) return 0;
    if (sc_cmp_raw(sraw, N) >= 0) return 0;
    if (sc_cmp_raw(sraw, NHALF) > 0) return 0;  // high-S malleability

    Jac Q;
    if (!point_decompress(Q, pub)) return 0;

    uint8_t digest[32];
    sha256(msg, msglen, digest);
    Sc z;
    sc_frombytes_be(z, digest);

    Sc w, u1, u2;
    sc_invert(w, s);
    sc_mul(u1, z, w);
    sc_mul(u2, r, w);

    ensure_g_table();

    // per-key wNAF(5) table: odd multiples [1,3,...,15]Q, Jacobian (a
    // batch normalization to affine would cost a field inversion — the
    // general jac_add in the ~43 table hits is cheaper than that)
    Jac q_tab[8];
    {
        Jac Q2;
        jac_double(Q2, Q);
        q_tab[0] = Q;
        for (int i = 1; i < 8; i++) jac_add(q_tab[i], q_tab[i - 1], Q2);
    }

    int8_t n1[257], n2[257];
    int l1 = wnaf(n1, u1, 8);
    int l2 = wnaf(n2, u2, 5);
    int top = (l1 > l2 ? l1 : l2) - 1;
    if (top < 0) return 0;  // u1 = u2 = 0 cannot yield x(R) = r != 0

    // interleaved Strauss: one shared doubling chain, table hits per digit
    Jac R;
    jac_infinity(R);
    for (int i = top; i >= 0; i--) {
        jac_double(R, R);
        int d1 = n1[i];
        if (d1 > 0) {
            jac_madd(R, R, G_TAB[(d1 - 1) >> 1]);
        } else if (d1 < 0) {
            Aff neg = G_TAB[(-d1 - 1) >> 1];
            Fp py = {{P[0], P[1], P[2], P[3]}};
            fp_sub(neg.y, py, neg.y);
            jac_madd(R, R, neg);
        }
        int d2 = n2[i];
        if (d2 > 0) {
            jac_add(R, R, q_tab[(d2 - 1) >> 1]);
        } else if (d2 < 0) {
            Jac neg = q_tab[(-d2 - 1) >> 1];
            Fp py = {{P[0], P[1], P[2], P[3]}};
            fp_sub(neg.Y, py, neg.Y);
            jac_add(R, R, neg);
        }
    }
    if (jac_is_infinity(R)) return 0;

    // r' == R.x (affine) mod n, compared in Jacobian coordinates: check
    // X == cand * Z^2 for cand in {r, r+n} (no field inversion). r < n
    // so r+n < 2n < 2^257; the r+n candidate only exists when r+n < p.
    Fp z2;
    fp_sq(z2, R.Z);
    for (int cand = 0; cand < 2; cand++) {
        uint64_t c[5] = {r.v[0], r.v[1], r.v[2], r.v[3], 0};
        if (cand == 1) {
            u128 carry = 0;
            for (int i = 0; i < 4; i++) {
                u128 s2 = (u128)c[i] + N[i] + carry;
                c[i] = (uint64_t)s2;
                carry = (uint64_t)(s2 >> 64);
            }
            c[4] = (uint64_t)carry;
            // candidate must be a canonical field element: r + n < p
            if (c[4] || fp_cmp_raw(c, P) >= 0) break;
        }
        Fp cf = {{c[0], c[1], c[2], c[3]}};
        Fp t;
        fp_mul(t, cf, z2);
        if (memcmp(t.v, R.X.v, sizeof t.v) == 0) return 1;
    }
    return 0;
}

}  // namespace tmnative
