// Width-w NAF recoding of a 256-bit scalar, shared by the ed25519 and
// secp256k1 verify paths (one definition so a recoding fix can never
// diverge the two). Input: 4 little-endian 64-bit limbs, value < the
// curve order (< 2^256). Output digits are odd in
// [-(2^(w-1)-1), 2^(w-1)-1] or 0; out needs 257 entries. Returns the
// number of significant digits. Variable-time is fine: verification
// inputs are public.
#pragma once
#include <cstdint>

namespace tmnative {

inline int wnaf_digits(int8_t out[257], const uint64_t limbs[4], int w) {
    typedef unsigned __int128 uu128;
    uint64_t v[5] = {limbs[0], limbs[1], limbs[2], limbs[3], 0};
    const int64_t half = 1 << (w - 1), full = 1 << w;
    int len = 0, i = 0;
    while (v[0] | v[1] | v[2] | v[3] | v[4]) {
        int64_t d = 0;
        if (v[0] & 1) {
            d = (int64_t)(v[0] & (uint64_t)(full - 1));
            if (d >= half) d -= full;
            if (d >= 0) {  // v -= d
                uu128 borrow = 0;
                uint64_t sub = (uint64_t)d;
                for (int l = 0; l < 5; l++) {
                    uu128 dd = (uu128)v[l] - (l == 0 ? sub : 0) - borrow;
                    v[l] = (uint64_t)dd;
                    borrow = (dd >> 64) ? 1 : 0;
                }
            } else {  // v += |d|
                uu128 carry = (uint64_t)(-d);
                for (int l = 0; l < 5 && carry; l++) {
                    uu128 s = (uu128)v[l] + carry;
                    v[l] = (uint64_t)s;
                    carry = (uint64_t)(s >> 64);
                }
            }
        }
        out[i] = (int8_t)d;
        if (d) len = i + 1;
        for (int l = 0; l < 4; l++) v[l] = (v[l] >> 1) | (v[l + 1] << 63);
        v[4] >>= 1;
        i++;
        if (i >= 257) break;
    }
    for (; i < 257; i++) out[i] = 0;
    return len;
}

}  // namespace tmnative
