// Sharded keyed-hash cache of per-pubkey decompression results, shared by
// the ed25519 and secp256k1 cores. In production the same validator set
// verifies every height, so the point-decompression square root (~10-14us
// of every verify) amortizes to a cache hit.
//
// Security notes carried over from the original ed25519 cache:
// - the hash is KEYED with per-process entropy: cache keys are
//   attacker-chosen bytes (a gossip peer controls pubkeys it claims), so
//   an unkeyed hash would allow hash-flooding one shard's chain;
// - failed-decompression (junk-key) entries are evicted first when a
//   shard fills, so spraying invalid pubkeys cannot flush the hot
//   validator keys.
#pragma once
#include <array>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <mutex>
#include <unordered_map>

namespace tmnative {

inline uint64_t pubcache_hash_seed() {
    static const uint64_t seed = [] {
        uint64_t s = 0x243F6A8885A308D3ull;  // fallback: pi digits
        timespec t;
        if (clock_gettime(CLOCK_MONOTONIC, &t) == 0)
            s ^= ((uint64_t)t.tv_sec << 32) ^ (uint64_t)t.tv_nsec;
        s ^= (uint64_t)(uintptr_t)&s;  // ASLR entropy
        return s;
    }();
    return seed;
}

template <size_t KEY_LEN, size_t VAL_LEN>
struct ShardedPubCache {
    using Key = std::array<uint8_t, KEY_LEN>;
    using Val = std::array<uint8_t, VAL_LEN + 1>;  // +1: valid flag

    struct Hash {
        size_t operator()(const Key& k) const {
            uint64_t h = pubcache_hash_seed();
            size_t i = 0;
            for (; i + 8 <= KEY_LEN; i += 8) {
                uint64_t w;
                memcpy(&w, k.data() + i, 8);
                h = (h ^ w) * 0x9E3779B97F4A7C15ull;  // splitmix64 round
                h ^= h >> 29;
            }
            if (i < KEY_LEN) {
                uint64_t w = 0;
                memcpy(&w, k.data() + i, KEY_LEN - i);
                h = (h ^ w) * 0x9E3779B97F4A7C15ull;
                h ^= h >> 29;
            }
            return (size_t)h;
        }
    };

    static const size_t NSHARD = 16;
    struct Shard {
        std::mutex mtx;
        std::unordered_map<Key, Val, Hash> map;
    };
    Shard shards[NSHARD];
    size_t shard_cap;

    explicit ShardedPubCache(size_t cap = 8192) : shard_cap(cap) {}

    // lookup WITHOUT a compute step: true + VAL_LEN bytes in `out` on a
    // positive hit; false on a miss or a cached-failure entry. Pairs
    // with put() for values produced by batch-amortized computations
    // (e.g. affine tables normalized by one shared inversion) that the
    // per-key compute callback of get() cannot express.
    bool lookup(const uint8_t* key_bytes, uint8_t* out) {
        Key key;
        memcpy(key.data(), key_bytes, KEY_LEN);
        Shard& sh = shards[Hash{}(key) & (NSHARD - 1)];
        std::lock_guard<std::mutex> g(sh.mtx);
        auto it = sh.map.find(key);
        if (it == sh.map.end() || !it->second[VAL_LEN]) return false;
        memcpy(out, it->second.data(), VAL_LEN);
        return true;
    }

    // Make room in a full shard: failed-decompression (junk-key) entries
    // go first; if every entry is valid, evict ONE arbitrary entry —
    // random replacement bounds an attacker streaming fresh VALID keys
    // to linear churn instead of whole-shard flushes of the hot
    // validator entries (the keyed hash keeps the victim untargetable).
    void evict_for_insert(Shard& sh) {
        if (sh.map.size() < shard_cap) return;
        for (auto it = sh.map.begin(); it != sh.map.end();) {
            if (!it->second[VAL_LEN]) it = sh.map.erase(it);
            else ++it;
        }
        if (sh.map.size() >= shard_cap) sh.map.erase(sh.map.begin());
    }

    void put(const uint8_t* key_bytes, const uint8_t* val_bytes) {
        Key key;
        memcpy(key.data(), key_bytes, KEY_LEN);
        Val entry{};
        memcpy(entry.data(), val_bytes, VAL_LEN);
        entry[VAL_LEN] = 1;
        Shard& sh = shards[Hash{}(key) & (NSHARD - 1)];
        std::lock_guard<std::mutex> g(sh.mtx);
        evict_for_insert(sh);
        sh.map.insert_or_assign(key, entry);
    }

    // compute: bool(const uint8_t* key, uint8_t* out_val) — runs outside
    // the shard lock on a miss; its result (incl. failure) is cached.
    // Returns compute's verdict; on success `out` holds VAL_LEN bytes.
    template <typename Fn>
    bool get(const uint8_t* key_bytes, uint8_t* out, Fn&& compute) {
        Key key;
        memcpy(key.data(), key_bytes, KEY_LEN);
        // shard by the keyed hash, not raw bytes: byte 0 is attacker-chosen
        Shard& sh = shards[Hash{}(key) & (NSHARD - 1)];
        {
            std::lock_guard<std::mutex> g(sh.mtx);
            auto it = sh.map.find(key);
            if (it != sh.map.end()) {
                if (!it->second[VAL_LEN]) return false;
                memcpy(out, it->second.data(), VAL_LEN);
                return true;
            }
        }
        Val entry{};
        bool ok = compute(key_bytes, entry.data());
        if (ok) {
            entry[VAL_LEN] = 1;
            memcpy(out, entry.data(), VAL_LEN);
        }
        std::lock_guard<std::mutex> g(sh.mtx);
        evict_for_insert(sh);
        sh.map.emplace(key, entry);
        return ok;
    }
};

}  // namespace tmnative
