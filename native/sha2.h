// SHA-256 / SHA-512 (FIPS 180-4), self-contained, little external surface.
// Used by the native verify core: ed25519 needs SHA-512 for the challenge
// scalar, secp256k1-ECDSA hashes messages with SHA-256 (matching the
// framework's Python path and the reference's usage).
#pragma once
#include <cstdint>
#include <cstring>
#include <cstddef>

namespace tmnative {

// ---------------------------------------------------------------- SHA-256

struct Sha256 {
    uint32_t h[8];
    uint64_t len = 0;
    uint8_t buf[64];
    size_t buflen = 0;

    Sha256() { reset(); }

    void reset() {
        static const uint32_t iv[8] = {
            0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
            0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
        memcpy(h, iv, sizeof h);
        len = 0;
        buflen = 0;
    }

    static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

    void block(const uint8_t* p) {
        static const uint32_t K[64] = {
            0x428a2f98u,0x71374491u,0xb5c0fbcfu,0xe9b5dba5u,0x3956c25bu,0x59f111f1u,
            0x923f82a4u,0xab1c5ed5u,0xd807aa98u,0x12835b01u,0x243185beu,0x550c7dc3u,
            0x72be5d74u,0x80deb1feu,0x9bdc06a7u,0xc19bf174u,0xe49b69c1u,0xefbe4786u,
            0x0fc19dc6u,0x240ca1ccu,0x2de92c6fu,0x4a7484aau,0x5cb0a9dcu,0x76f988dau,
            0x983e5152u,0xa831c66du,0xb00327c8u,0xbf597fc7u,0xc6e00bf3u,0xd5a79147u,
            0x06ca6351u,0x14292967u,0x27b70a85u,0x2e1b2138u,0x4d2c6dfcu,0x53380d13u,
            0x650a7354u,0x766a0abbu,0x81c2c92eu,0x92722c85u,0xa2bfe8a1u,0xa81a664bu,
            0xc24b8b70u,0xc76c51a3u,0xd192e819u,0xd6990624u,0xf40e3585u,0x106aa070u,
            0x19a4c116u,0x1e376c08u,0x2748774cu,0x34b0bcb5u,0x391c0cb3u,0x4ed8aa4au,
            0x5b9cca4fu,0x682e6ff3u,0x748f82eeu,0x78a5636fu,0x84c87814u,0x8cc70208u,
            0x90befffau,0xa4506cebu,0xbef9a3f7u,0xc67178f2u};
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
                   (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + K[i] + w[i];
            uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t* p, size_t n) {
        len += n;
        if (buflen) {
            while (n && buflen < 64) { buf[buflen++] = *p++; n--; }
            if (buflen == 64) { block(buf); buflen = 0; }
        }
        while (n >= 64) { block(p); p += 64; n -= 64; }
        while (n) { buf[buflen++] = *p++; n--; }
    }

    void final(uint8_t out[32]) {
        uint64_t bitlen = len * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (buflen != 56) update(&z, 1);
        uint8_t lb[8];
        for (int i = 0; i < 8; i++) lb[i] = uint8_t(bitlen >> (56 - 8 * i));
        update(lb, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = uint8_t(h[i] >> 24);
            out[4 * i + 1] = uint8_t(h[i] >> 16);
            out[4 * i + 2] = uint8_t(h[i] >> 8);
            out[4 * i + 3] = uint8_t(h[i]);
        }
    }
};

inline void sha256(const uint8_t* p, size_t n, uint8_t out[32]) {
    Sha256 s;
    s.update(p, n);
    s.final(out);
}

// ---------------------------------------------------------------- SHA-512

struct Sha512 {
    uint64_t h[8];
    uint64_t lenlo = 0;  // messages < 2^64 bytes
    uint8_t buf[128];
    size_t buflen = 0;

    Sha512() { reset(); }

    void reset() {
        static const uint64_t iv[8] = {
            0x6a09e667f3bcc908ull, 0xbb67ae8584caa73bull, 0x3c6ef372fe94f82bull,
            0xa54ff53a5f1d36f1ull, 0x510e527fade682d1ull, 0x9b05688c2b3e6c1full,
            0x1f83d9abfb41bd6bull, 0x5be0cd19137e2179ull};
        memcpy(h, iv, sizeof h);
        lenlo = 0;
        buflen = 0;
    }

    static uint64_t rotr(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

    void block(const uint8_t* p) {
        static const uint64_t K[80] = {
            0x428a2f98d728ae22ull,0x7137449123ef65cdull,0xb5c0fbcfec4d3b2full,0xe9b5dba58189dbbcull,
            0x3956c25bf348b538ull,0x59f111f1b605d019ull,0x923f82a4af194f9bull,0xab1c5ed5da6d8118ull,
            0xd807aa98a3030242ull,0x12835b0145706fbeull,0x243185be4ee4b28cull,0x550c7dc3d5ffb4e2ull,
            0x72be5d74f27b896full,0x80deb1fe3b1696b1ull,0x9bdc06a725c71235ull,0xc19bf174cf692694ull,
            0xe49b69c19ef14ad2ull,0xefbe4786384f25e3ull,0x0fc19dc68b8cd5b5ull,0x240ca1cc77ac9c65ull,
            0x2de92c6f592b0275ull,0x4a7484aa6ea6e483ull,0x5cb0a9dcbd41fbd4ull,0x76f988da831153b5ull,
            0x983e5152ee66dfabull,0xa831c66d2db43210ull,0xb00327c898fb213full,0xbf597fc7beef0ee4ull,
            0xc6e00bf33da88fc2ull,0xd5a79147930aa725ull,0x06ca6351e003826full,0x142929670a0e6e70ull,
            0x27b70a8546d22ffcull,0x2e1b21385c26c926ull,0x4d2c6dfc5ac42aedull,0x53380d139d95b3dfull,
            0x650a73548baf63deull,0x766a0abb3c77b2a8ull,0x81c2c92e47edaee6ull,0x92722c851482353bull,
            0xa2bfe8a14cf10364ull,0xa81a664bbc423001ull,0xc24b8b70d0f89791ull,0xc76c51a30654be30ull,
            0xd192e819d6ef5218ull,0xd69906245565a910ull,0xf40e35855771202aull,0x106aa07032bbd1b8ull,
            0x19a4c116b8d2d0c8ull,0x1e376c085141ab53ull,0x2748774cdf8eeb99ull,0x34b0bcb5e19b48a8ull,
            0x391c0cb3c5c95a63ull,0x4ed8aa4ae3418acbull,0x5b9cca4f7763e373ull,0x682e6ff3d6b2b8a3ull,
            0x748f82ee5defb2fcull,0x78a5636f43172f60ull,0x84c87814a1f0ab72ull,0x8cc702081a6439ecull,
            0x90befffa23631e28ull,0xa4506cebde82bde9ull,0xbef9a3f7b2c67915ull,0xc67178f2e372532bull,
            0xca273eceea26619cull,0xd186b8c721c0c207ull,0xeada7dd6cde0eb1eull,0xf57d4f7fee6ed178ull,
            0x06f067aa72176fbaull,0x0a637dc5a2c898a6ull,0x113f9804bef90daeull,0x1b710b35131c471bull,
            0x28db77f523047d84ull,0x32caab7b40c72493ull,0x3c9ebe0a15c9bebcull,0x431d67c49c100d4cull,
            0x4cc5d4becb3e42b6ull,0x597f299cfc657e2aull,0x5fcb6fab3ad6faecull,0x6c44198c4a475817ull};
        uint64_t w[80];
        for (int i = 0; i < 16; i++) {
            w[i] = 0;
            for (int j = 0; j < 8; j++) w[i] = (w[i] << 8) | p[8 * i + j];
        }
        for (int i = 16; i < 80; i++) {
            uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
            uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 80; i++) {
            uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
            uint64_t ch = (e & f) ^ (~e & g);
            uint64_t t1 = hh + S1 + ch + K[i] + w[i];
            uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
            uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint64_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t* p, size_t n) {
        lenlo += n;
        if (buflen) {
            while (n && buflen < 128) { buf[buflen++] = *p++; n--; }
            if (buflen == 128) { block(buf); buflen = 0; }
        }
        while (n >= 128) { block(p); p += 128; n -= 128; }
        while (n) { buf[buflen++] = *p++; n--; }
    }

    void final(uint8_t out[64]) {
        uint64_t bitlen = lenlo * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (buflen != 112) update(&z, 1);
        uint8_t lb[16] = {0};  // high 64 bits of the 128-bit length stay 0
        for (int i = 0; i < 8; i++) lb[8 + i] = uint8_t(bitlen >> (56 - 8 * i));
        update(lb, 16);
        for (int i = 0; i < 8; i++)
            for (int j = 0; j < 8; j++) out[8 * i + j] = uint8_t(h[i] >> (56 - 8 * j));
    }
};

}  // namespace tmnative
