// RFC-6962-style merkle root — the native core behind
// crypto/merkle.py:hash_from_byte_slices (0x00 leaf / 0x01 inner domain
// separation, largest-power-of-two-less-than split). Bit-exact parity with
// the Python implementation; reference analog crypto/merkle/simple_tree.go.
//
// The tree root is the hottest host-side hash path in a commit round: tx
// roots, header field roots, part-set roots, ABCI results roots and the
// kvstore example's app hash all fold through it (profiled at ~10% of a
// loaded node's CPU in Python).
#include <cstddef>
#include <cstdint>

#include "sha2.h"

namespace {

void leaf_hash(const uint8_t* p, size_t n, uint8_t out[32]) {
    tmnative::Sha256 h;
    const uint8_t pre = 0x00;
    h.update(&pre, 1);
    h.update(p, n);
    h.final(out);
}

void inner_hash(const uint8_t l[32], const uint8_t r[32], uint8_t out[32]) {
    tmnative::Sha256 h;
    const uint8_t pre = 0x01;
    h.update(&pre, 1);
    h.update(l, 32);
    h.update(r, 32);
    h.final(out);
}

void node_hash(const uint8_t* data, const uint64_t* off, size_t lo, size_t hi,
               uint8_t out[32]) {
    const size_t n = hi - lo;
    if (n == 1) {
        leaf_hash(data + off[lo], (size_t)(off[lo + 1] - off[lo]), out);
        return;
    }
    size_t k = 1;
    while (k * 2 < n) k *= 2;
    uint8_t l[32], r[32];
    node_hash(data, off, lo, lo + k, l);
    node_hash(data, off, lo + k, hi, r);
    inner_hash(l, r, out);
}

}  // namespace

extern "C" {

// items are concatenated in `data`; offsets has n+1 entries delimiting them.
void tm_merkle_root(const uint8_t* data, const uint64_t* offsets, size_t n,
                    uint8_t* out32) {
    if (n == 0) {
        tmnative::sha256(data, 0, out32);  // hash of the empty string
        return;
    }
    node_hash(data, offsets, 0, n, out32);
}

}  // extern "C"
