// Batch verification C API — the native backend of crypto/batch.py.
//
// Layout matches the ctypes binding in tendermint_tpu/crypto/native.py:
// fixed-stride pubkey/sig arrays, variable-length messages via a flat
// buffer + offsets. Work is sharded across hardware threads; each
// signature is independent so this is embarrassingly parallel.
#include <cstdint>
#include <cstddef>
#include <thread>
#include <vector>
#include <algorithm>

namespace tmnative {
extern "C" int tm_ed25519_verify(const uint8_t*, const uint8_t*, size_t, const uint8_t*);
extern "C" int tm_secp256k1_verify(const uint8_t*, const uint8_t*, size_t, const uint8_t*);
extern "C" void tm_secp256k1_verify_range(const uint8_t*, const uint8_t*,
                                          const uint64_t*, const uint8_t*,
                                          size_t, size_t, uint8_t*);
extern "C" void tm_ed25519_verify_range(const uint8_t*, const uint8_t*,
                                        const uint64_t*, const uint8_t*,
                                        size_t, size_t, uint8_t*);
}

using tmnative::tm_ed25519_verify;
using tmnative::tm_ed25519_verify_range;
using tmnative::tm_secp256k1_verify;
using tmnative::tm_secp256k1_verify_range;

namespace {

// shard [0, n) into one contiguous range per worker; f(lo, hi) owns its
// range exclusively (the secp batched core amortizes two inversions per
// 64-signature sub-chunk, so work must arrive as ranges, not indices)
template <typename F>
void parallel_ranges(size_t n, F f) {
    unsigned hw = std::thread::hardware_concurrency();
    size_t workers = std::min<size_t>(std::max(1u, hw), (n + 63) / 64);
    if (workers <= 1) {
        f((size_t)0, n);
        return;
    }
    std::vector<std::thread> ts;
    ts.reserve(workers);
    size_t chunk = (n + workers - 1) / workers;
    for (size_t w = 0; w < workers; w++) {
        size_t lo = w * chunk, hi = std::min(n, lo + chunk);
        if (lo >= hi) break;
        ts.emplace_back([=] { f(lo, hi); });
    }
    for (auto& t : ts) t.join();
}

template <typename F>
void parallel_for(size_t n, F f) {
    unsigned hw = std::thread::hardware_concurrency();
    size_t workers = std::min<size_t>(std::max(1u, hw), n);
    if (workers <= 1 || n < 16) {
        for (size_t i = 0; i < n; i++) f(i);
        return;
    }
    std::vector<std::thread> ts;
    ts.reserve(workers);
    size_t chunk = (n + workers - 1) / workers;
    for (size_t w = 0; w < workers; w++) {
        size_t lo = w * chunk, hi = std::min(n, lo + chunk);
        if (lo >= hi) break;
        ts.emplace_back([=] {
            for (size_t i = lo; i < hi; i++) f(i);
        });
    }
    for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// pubs: n*32, sigs: n*64, msgs: flat buffer, offsets: n+1 entries
void tm_ed25519_verify_batch(const uint8_t* pubs, const uint8_t* msgs,
                             const uint64_t* offsets, const uint8_t* sigs,
                             size_t n, uint8_t* out) {
    if (n < 4) {
        // the batched core pays one shared inversion ladder per
        // sub-chunk; below ~4 signatures the single-shot path wins
        for (size_t i = 0; i < n; i++)
            out[i] = (uint8_t)tm_ed25519_verify(
                pubs + 32 * i, msgs + offsets[i],
                (size_t)(offsets[i + 1] - offsets[i]), sigs + 64 * i);
        return;
    }
    parallel_ranges(n, [&](size_t lo, size_t hi) {
        tm_ed25519_verify_range(pubs, msgs, offsets, sigs, lo, hi, out);
    });
}

// pubs: n*33, sigs: n*64
void tm_secp256k1_verify_batch(const uint8_t* pubs, const uint8_t* msgs,
                               const uint64_t* offsets, const uint8_t* sigs,
                               size_t n, uint8_t* out) {
    if (n < 4) {
        // the batched core pays one scalar + one field inversion ladder
        // per sub-chunk; below ~4 signatures the single-shot path wins
        for (size_t i = 0; i < n; i++)
            out[i] = (uint8_t)tm_secp256k1_verify(
                pubs + 33 * i, msgs + offsets[i],
                (size_t)(offsets[i + 1] - offsets[i]), sigs + 64 * i);
        return;
    }
    parallel_ranges(n, [&](size_t lo, size_t hi) {
        tm_secp256k1_verify_range(pubs, msgs, offsets, sigs, lo, hi, out);
    });
}

int tm_native_abi_version(void) { return 1; }

}  // extern "C"
