// Ed25519 signature verification (RFC 8032), clean-room C++.
//
// Role in the framework: the native-parity component demanded by the
// reference's vendored C library (crypto/secp256k1/internal, SURVEY §2) —
// the CPU fallback path of the batch verifier for builds without a TPU,
// mirroring the reference's cgo/nocgo dual build. The TPU path lives in
// tendermint_tpu/ops (JAX); this file shares no code with either.
//
// Field arithmetic: GF(2^255-19) as 5x51-bit limbs, products via unsigned
// __int128. Points: extended twisted Edwards coordinates (a = -1), unified
// add / dedicated double. Double-scalar mult: 4-bit windows, interleaved.
#include <cstdint>
#include <cstring>
#include <array>
#include <mutex>
#include <thread>
#include <unordered_map>
#include "minv.h"
#include "wnaf.h"
#include "pubcache.h"
#include <vector>
#include <ctime>
#include <dlfcn.h>
#include "sha2.h"

namespace tmnative {

typedef unsigned __int128 u128;

struct Fe {
    uint64_t v[5];  // value = sum v[i] * 2^(51 i), limbs < ~2^52 between carries
};

static const uint64_t MASK51 = (1ull << 51) - 1;

static void fe_zero(Fe& o) { memset(o.v, 0, sizeof o.v); }
static void fe_one(Fe& o) { fe_zero(o); o.v[0] = 1; }
static void fe_copy(Fe& o, const Fe& a) { memcpy(o.v, a.v, sizeof o.v); }

static void fe_add(Fe& o, const Fe& a, const Fe& b) {
    for (int i = 0; i < 5; i++) o.v[i] = a.v[i] + b.v[i];
}

// o = a - b. Adds 2p first so limbs stay non-negative.
static void fe_sub(Fe& o, const Fe& a, const Fe& b) {
    // 2p = 2^256 - 38: per-limb constants 2*(2^51-19), 2*(2^51-1)...
    o.v[0] = a.v[0] + 0xFFFFFFFFFFFDAull - b.v[0];
    o.v[1] = a.v[1] + 0xFFFFFFFFFFFFEull - b.v[1];
    o.v[2] = a.v[2] + 0xFFFFFFFFFFFFEull - b.v[2];
    o.v[3] = a.v[3] + 0xFFFFFFFFFFFFEull - b.v[3];
    o.v[4] = a.v[4] + 0xFFFFFFFFFFFFEull - b.v[4];
}

static void fe_carry(Fe& o) {
    uint64_t c;
    for (int r = 0; r < 2; r++) {
        c = o.v[0] >> 51; o.v[0] &= MASK51; o.v[1] += c;
        c = o.v[1] >> 51; o.v[1] &= MASK51; o.v[2] += c;
        c = o.v[2] >> 51; o.v[2] &= MASK51; o.v[3] += c;
        c = o.v[3] >> 51; o.v[3] &= MASK51; o.v[4] += c;
        c = o.v[4] >> 51; o.v[4] &= MASK51; o.v[0] += c * 19;
    }
}

static void fe_mul(Fe& o, const Fe& a, const Fe& b) {
    u128 t0 = (u128)a.v[0] * b.v[0] + (u128)(19 * a.v[1]) * b.v[4] +
              (u128)(19 * a.v[2]) * b.v[3] + (u128)(19 * a.v[3]) * b.v[2] +
              (u128)(19 * a.v[4]) * b.v[1];
    u128 t1 = (u128)a.v[0] * b.v[1] + (u128)a.v[1] * b.v[0] +
              (u128)(19 * a.v[2]) * b.v[4] + (u128)(19 * a.v[3]) * b.v[3] +
              (u128)(19 * a.v[4]) * b.v[2];
    u128 t2 = (u128)a.v[0] * b.v[2] + (u128)a.v[1] * b.v[1] +
              (u128)a.v[2] * b.v[0] + (u128)(19 * a.v[3]) * b.v[4] +
              (u128)(19 * a.v[4]) * b.v[3];
    u128 t3 = (u128)a.v[0] * b.v[3] + (u128)a.v[1] * b.v[2] +
              (u128)a.v[2] * b.v[1] + (u128)a.v[3] * b.v[0] +
              (u128)(19 * a.v[4]) * b.v[4];
    u128 t4 = (u128)a.v[0] * b.v[4] + (u128)a.v[1] * b.v[3] +
              (u128)a.v[2] * b.v[2] + (u128)a.v[3] * b.v[1] +
              (u128)a.v[4] * b.v[0];
    uint64_t c;
    uint64_t r0, r1, r2, r3, r4;
    r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51); t1 += c;
    r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51); t2 += c;
    r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51); t3 += c;
    r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51); t4 += c;
    r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
    r0 += c * 19;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    o.v[0] = r0; o.v[1] = r1; o.v[2] = r2; o.v[3] = r3; o.v[4] = r4;
}

// dedicated squaring: 15 64x64 products vs fe_mul's 25. From the limb
// product t_k = sum_{i+j=k} a_i a_j with t_{5+k} folded into t_k by *19:
//   r0 = a0^2        + 38(a1 a4) + 38(a2 a3)
//   r1 = 2 a0 a1     + 38(a2 a4) + 19 a3^2
//   r2 = 2 a0 a2     + a1^2      + 38(a3 a4)
//   r3 = 2 a0 a3     + 2 a1 a2   + 19 a4^2
//   r4 = 2 a0 a4     + 2 a1 a3   + a2^2
// Bounds: limbs < 2^52, 38*a < 2^58, so each u128 term < 2^110 and the
// 3-term sums stay far below 2^128 — same headroom as fe_mul.
static void fe_sq(Fe& o, const Fe& a) {
    const uint64_t d0 = 2 * a.v[0], d1 = 2 * a.v[1];
    const uint64_t a3_19 = 19 * a.v[3], a4_19 = 19 * a.v[4];
    const uint64_t a3_38 = 2 * a3_19, a4_38 = 2 * a4_19;
    u128 t0 = (u128)a.v[0] * a.v[0] + (u128)a.v[1] * a4_38 +
              (u128)a.v[2] * a3_38;
    u128 t1 = (u128)d0 * a.v[1] + (u128)a.v[2] * a4_38 +
              (u128)a.v[3] * a3_19;
    u128 t2 = (u128)d0 * a.v[2] + (u128)a.v[1] * a.v[1] +
              (u128)a.v[3] * a4_38;
    u128 t3 = (u128)d0 * a.v[3] + (u128)d1 * a.v[2] +
              (u128)a.v[4] * a4_19;
    u128 t4 = (u128)d0 * a.v[4] + (u128)d1 * a.v[3] +
              (u128)a.v[2] * a.v[2];
    uint64_t c;
    uint64_t r0, r1, r2, r3, r4;
    r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51); t1 += c;
    r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51); t2 += c;
    r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51); t3 += c;
    r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51); t4 += c;
    r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
    r0 += c * 19;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    o.v[0] = r0; o.v[1] = r1; o.v[2] = r2; o.v[3] = r3; o.v[4] = r4;
}

// canonical little-endian 32 bytes
static void fe_tobytes(uint8_t out[32], const Fe& a) {
    Fe t;
    fe_copy(t, a);
    fe_carry(t);
    // fully reduce: add 19, propagate, drop bit 255, then subtract the 19 trick
    uint64_t q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    uint64_t c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    uint64_t w[4];
    w[0] = t.v[0] | (t.v[1] << 51);
    w[1] = (t.v[1] >> 13) | (t.v[2] << 38);
    w[2] = (t.v[2] >> 26) | (t.v[3] << 25);
    w[3] = (t.v[3] >> 39) | (t.v[4] << 12);
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++) out[8 * i + j] = uint8_t(w[i] >> (8 * j));
}

static void fe_frombytes(Fe& o, const uint8_t in[32]) {
    uint64_t w[4];
    for (int i = 0; i < 4; i++) {
        w[i] = 0;
        for (int j = 7; j >= 0; j--) w[i] = (w[i] << 8) | in[8 * i + j];
    }
    o.v[0] = w[0] & MASK51;
    o.v[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
    o.v[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
    o.v[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
    o.v[4] = (w[3] >> 12) & MASK51;  // top bit dropped (sign bit)
}

static bool fe_iszero(const Fe& a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    uint8_t r = 0;
    for (int i = 0; i < 32; i++) r |= b[i];
    return r == 0;
}

static bool fe_eq(const Fe& a, const Fe& b) {
    uint8_t x[32], y[32];
    fe_tobytes(x, a);
    fe_tobytes(y, b);
    return memcmp(x, y, 32) == 0;
}

static int fe_parity(const Fe& a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    return b[0] & 1;
}

static void fe_neg(Fe& o, const Fe& a) {
    Fe z;
    fe_zero(z);
    fe_sub(o, z, a);
    fe_carry(o);
}

// o = a^(2^n) by repeated squaring into o (a may alias o)
static void fe_sqn(Fe& o, const Fe& a, int n) {
    fe_copy(o, a);
    for (int i = 0; i < n; i++) fe_sq(o, o);
}

// o = a^(p-2): inversion by Fermat (addition chain from the curve literature)
static void fe_invert(Fe& o, const Fe& a) {
    Fe t0, t1, t2, t3;
    fe_sq(t0, a);               // a^2
    fe_sq(t1, t0); fe_sq(t1, t1);  // a^8
    fe_mul(t1, t1, a);          // a^9
    fe_mul(t0, t0, t1);         // a^11
    fe_sq(t2, t0);              // a^22
    fe_mul(t1, t1, t2);         // a^31 = a^(2^5-1)
    fe_sqn(t2, t1, 5); fe_mul(t1, t2, t1);   // 2^10-1
    fe_sqn(t2, t1, 10); fe_mul(t2, t2, t1);  // 2^20-1
    fe_sqn(t3, t2, 20); fe_mul(t2, t3, t2);  // 2^40-1
    fe_sqn(t2, t2, 10); fe_mul(t1, t2, t1);  // 2^50-1
    fe_sqn(t2, t1, 50); fe_mul(t2, t2, t1);  // 2^100-1
    fe_sqn(t3, t2, 100); fe_mul(t2, t3, t2); // 2^200-1
    fe_sqn(t2, t2, 50); fe_mul(t1, t2, t1);  // 2^250-1
    fe_sqn(t1, t1, 5);
    fe_mul(o, t1, t0);          // 2^255-21 = p-2
}

// o = a^((p-5)/8), used by the combined sqrt-ratio in decompression
static void fe_pow22523(Fe& o, const Fe& a) {
    Fe t0, t1, t2;
    fe_sq(t0, a);
    fe_sq(t1, t0); fe_sq(t1, t1);
    fe_mul(t1, t1, a);          // a^9
    fe_mul(t0, t0, t1);         // a^11
    fe_sq(t0, t0);              // a^22
    fe_mul(t0, t0, t1);         // a^31
    fe_sqn(t1, t0, 5); fe_mul(t0, t1, t0);
    fe_sqn(t1, t0, 10); fe_mul(t1, t1, t0);
    fe_sqn(t2, t1, 20); fe_mul(t1, t2, t1);
    fe_sqn(t1, t1, 10); fe_mul(t0, t1, t0);
    fe_sqn(t1, t0, 50); fe_mul(t1, t1, t0);
    fe_sqn(t2, t1, 100); fe_mul(t1, t2, t1);
    fe_sqn(t1, t1, 50); fe_mul(t0, t1, t0);
    fe_sq(t0, t0); fe_sq(t0, t0);
    fe_mul(o, t0, a);
}

// curve constants
static const Fe FE_D = {{0x34dca135978a3ull, 0x1a8283b156ebdull, 0x5e7a26001c029ull,
                         0x739c663a03cbbull, 0x52036cee2b6ffull}};
static const Fe FE_SQRTM1 = {{0x61b274a0ea0b0ull, 0xd5a5fc8f189dull, 0x7ef5e9cbd0c60ull,
                              0x78595a6804c9eull, 0x2b8324804fc1dull}};

struct Point {  // extended coordinates: x = X/Z, y = Y/Z, T = XY/Z
    Fe X, Y, Z, T;
};

static void pt_identity(Point& o) {
    fe_zero(o.X);
    fe_one(o.Y);
    fe_one(o.Z);
    fe_zero(o.T);
}

// unified addition (RFC 8032 §5.1.4)
static void pt_add(Point& o, const Point& p, const Point& q) {
    Fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X); fe_carry(t);
    fe_sub(a, q.Y, q.X); fe_carry(a);
    fe_mul(a, t, a);                       // A = (Y1-X1)(Y2-X2)
    fe_add(t, p.Y, p.X);
    fe_add(b, q.Y, q.X);
    fe_mul(b, t, b);                       // B = (Y1+X1)(Y2+X2)
    fe_mul(c, p.T, q.T);
    fe_mul(c, c, FE_D);
    fe_add(c, c, c);                       // C = 2 d T1 T2
    fe_carry(c);
    fe_mul(d, p.Z, q.Z);
    fe_add(d, d, d);                       // D = 2 Z1 Z2
    fe_carry(d);
    fe_sub(e, b, a); fe_carry(e);          // E = B - A
    fe_sub(f, d, c); fe_carry(f);          // F = D - C
    fe_add(g, d, c); fe_carry(g);          // G = D + C
    fe_add(h, b, a); fe_carry(h);          // H = B + A
    fe_mul(o.X, e, f);
    fe_mul(o.Y, g, h);
    fe_mul(o.T, e, h);
    fe_mul(o.Z, f, g);
}

static void pt_double(Point& o, const Point& p) {
    Fe a, b, c, e, f, g, h, t;
    fe_sq(a, p.X);                         // A = X1^2
    fe_sq(b, p.Y);                         // B = Y1^2
    fe_sq(c, p.Z);
    fe_add(c, c, c); fe_carry(c);          // C = 2 Z1^2
    fe_add(h, a, b); fe_carry(h);          // H = A + B
    fe_add(t, p.X, p.Y); fe_carry(t);
    fe_sq(t, t);
    fe_sub(e, h, t); fe_carry(e);          // E = H - (X1+Y1)^2
    fe_sub(g, a, b); fe_carry(g);          // G = A - B
    fe_add(f, c, g); fe_carry(f);          // F = C + G
    fe_mul(o.X, e, f);
    fe_mul(o.Y, g, h);
    fe_mul(o.T, e, h);
    fe_mul(o.Z, f, g);
}

static void pt_neg(Point& o, const Point& p) {
    fe_neg(o.X, p.X);
    fe_copy(o.Y, p.Y);
    fe_copy(o.Z, p.Z);
    fe_neg(o.T, p.T);
}

static void pt_tobytes(uint8_t out[32], const Point& p) {
    Fe zi, x, y;
    fe_invert(zi, p.Z);
    fe_mul(x, p.X, zi);
    fe_mul(y, p.Y, zi);
    fe_tobytes(out, y);
    out[31] ^= uint8_t(fe_parity(x) << 7);
}

// strict canonicality: is the low-255-bit little-endian y < p ?
// (shared by decompression and the batch-prep structural checks)
static bool y_canonical(const uint8_t in[32]) {
    static const uint8_t PBYTES[32] = {
        0xed,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
        0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
        0xff,0xff,0xff,0x7f};
    uint8_t ycopy[32];
    memcpy(ycopy, in, 32);
    ycopy[31] &= 0x7f;
    for (int i = 31; i >= 0; i--) {
        if (ycopy[i] < PBYTES[i]) return true;
        if (ycopy[i] > PBYTES[i]) return false;
    }
    return false;  // y == p
}

// decompress per RFC 8032 §5.1.3; returns false on invalid encoding
static bool pt_frombytes(Point& o, const uint8_t in[32]) {
    if (!y_canonical(in)) return false;  // reject non-canonical y (y >= p)

    int sign = in[31] >> 7;
    Fe y, y2, u, v, x, t, chk;
    fe_frombytes(y, in);
    fe_sq(y2, y);
    Fe one;
    fe_one(one);
    fe_sub(u, y2, one); fe_carry(u);       // u = y^2 - 1
    fe_mul(v, y2, FE_D);
    fe_add(v, v, one); fe_carry(v);        // v = d y^2 + 1
    // x = u v^3 (u v^7)^((p-5)/8)
    Fe v3, v7;
    fe_sq(v3, v); fe_mul(v3, v3, v);       // v^3
    fe_sq(v7, v3); fe_mul(v7, v7, v);      // v^7
    fe_mul(t, u, v7);
    fe_pow22523(t, t);
    fe_mul(x, u, v3);
    fe_mul(x, x, t);
    // check v x^2 == ±u
    fe_sq(chk, x);
    fe_mul(chk, chk, v);
    Fe negu;
    fe_neg(negu, u);
    if (!fe_eq(chk, u)) {
        if (!fe_eq(chk, negu)) return false;
        fe_mul(x, x, FE_SQRTM1);
    }
    if (fe_iszero(x) && sign) return false;  // -0 is invalid
    if (fe_parity(x) != sign) fe_neg(x, x);
    fe_copy(o.X, x);
    fe_copy(o.Y, y);
    fe_one(o.Z);
    fe_mul(o.T, x, y);
    return true;
}

// ---------------------------------------------------------------- scalars

// group order L = 2^252 + 27742317777372353535851937790883648493 (little-endian)
static const uint8_t LBYTES[32] = {
    0xed,0xd3,0xf5,0x5c,0x1a,0x63,0x12,0x58,0xd6,0x9c,0xf7,0xa2,0xde,0xf9,
    0xde,0x14,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,
    0x00,0x00,0x00,0x10};

static bool sc_canonical(const uint8_t s[32]) {  // s < L ?
    for (int i = 31; i >= 0; i--) {
        if (s[i] < LBYTES[i]) return true;
        if (s[i] > LBYTES[i]) return false;
    }
    return false;  // s == L
}

// ---------------------------------------------------------------- verify

// ------------------------- Strauss-wNAF machinery for the strict verify
//
// Deliberate design note: random-linear-combination batch verification is
// NOT used anywhere in this backend. On this cofactor-8 curve an RLC
// batch check and the strict per-signature check disagree on
// torsion-crafted signatures (a malicious validator can mint two votes
// whose torsion residues cancel: they batch-accept together but
// serial-reject individually), and this backend must stay bit-consistent
// with the OpenSSL serial path and the per-lane TPU kernel it shadows —
// routing is host-dependent, so any semantic gap is a consensus-split
// vector. Speed comes from evaluating the SAME strict equation better:
// one shared doubling chain for both scalars, wNAF(8) over a static
// basepoint table in precomputed (y+x, y-x, 2dxy) form, wNAF(5) over the
// per-key table.

struct Niels {  // affine precomputed point: (y+x, y-x, 2 d x y)
    Fe yplusx, yminusx, t2d;
};

// mixed add o = p + q, q affine-precomputed (saves the Z2 multiply)
static void pt_madd(Point& o, const Point& p, const Niels& q) {
    Fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X); fe_carry(t);
    fe_mul(a, t, q.yminusx);               // A = (Y1-X1)(y2-x2)
    fe_add(t, p.Y, p.X);
    fe_mul(b, t, q.yplusx);                // B = (Y1+X1)(y2+x2)
    fe_mul(c, p.T, q.t2d);                 // C = 2 d T1 x2 y2
    fe_add(d, p.Z, p.Z); fe_carry(d);      // D = 2 Z1
    fe_sub(e, b, a); fe_carry(e);
    fe_sub(f, d, c); fe_carry(f);
    fe_add(g, d, c); fe_carry(g);
    fe_add(h, b, a); fe_carry(h);
    fe_mul(o.X, e, f);
    fe_mul(o.Y, g, h);
    fe_mul(o.T, e, h);
    fe_mul(o.Z, f, g);
}

// mixed subtract o = p - q: -q swaps (y+x, y-x) and negates t2d
static void pt_msub(Point& o, const Point& p, const Niels& q) {
    Fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X); fe_carry(t);
    fe_mul(a, t, q.yplusx);
    fe_add(t, p.Y, p.X);
    fe_mul(b, t, q.yminusx);
    fe_mul(c, p.T, q.t2d);
    fe_neg(c, c); fe_carry(c);
    fe_add(d, p.Z, p.Z); fe_carry(d);
    fe_sub(e, b, a); fe_carry(e);
    fe_sub(f, d, c); fe_carry(f);
    fe_add(g, d, c); fe_carry(g);
    fe_add(h, b, a); fe_carry(h);
    fe_mul(o.X, e, f);
    fe_mul(o.Y, g, h);
    fe_mul(o.T, e, h);
    fe_mul(o.Z, f, g);
}

// width-w NAF of a 32-byte little-endian scalar (< L); shared recoder
// lives in wnaf.h so the two curves' digit logic can never diverge
static int wnaf_le(int8_t out[257], const uint8_t k[32], int w) {
    uint64_t v[4] = {0, 0, 0, 0};
    for (int i = 0; i < 4; i++)
        for (int j = 7; j >= 0; j--) v[i] = (v[i] << 8) | k[8 * i + j];
    return wnaf_digits(out, v, w);
}

// base point B
static bool basepoint(Point& B) {
    static const uint8_t BBYTES[32] = {
        0x58,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,
        0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,0x66,
        0x66,0x66,0x66,0x66};
    return pt_frombytes(B, BBYTES);
}

// static wNAF(8) basepoint table: [1,3,...,127]B in Niels form, built once
// (thread-safe via C++11 magic static; the batch entry runs on a pool)
static Niels B_TAB[64];

static void build_b_table() {
    Point B;
    basepoint(B);
    Point B2, cur = B;
    pt_double(B2, B);
    Point ext[64];
    ext[0] = B;
    for (int i = 1; i < 64; i++) {
        pt_add(cur, cur, B2);
        ext[i] = cur;
    }
    // batch-normalize to affine: one inversion for all 64 Z's (minv.h)
    Fe* zptr[64];
    Fe zinvs[64];
    for (int i = 0; i < 64; i++) zptr[i] = &ext[i].Z;
    Fe one;
    fe_one(one);
    batch_invert(zptr, zinvs, 64, one, fe_mul, fe_invert);
    for (int i = 0; i < 64; i++) {
        Fe x, y, xy;
        fe_mul(x, ext[i].X, zinvs[i]);
        fe_mul(y, ext[i].Y, zinvs[i]);
        fe_add(B_TAB[i].yplusx, y, x);
        fe_carry(B_TAB[i].yplusx);
        fe_sub(B_TAB[i].yminusx, y, x);
        fe_carry(B_TAB[i].yminusx);
        fe_mul(xy, x, y);
        fe_mul(xy, xy, FE_D);
        fe_add(B_TAB[i].t2d, xy, xy);
        fe_carry(B_TAB[i].t2d);
    }
}

static void ensure_b_table() {
    static const bool ready = (build_b_table(), true);
    (void)ready;
}

// ------------------------------------------------ fast reduction mod L
//
// Shared by the verify path and the batch-prep path (~100ns): write
// h = h1*2^252 + h0 and fold with 2^252 === -c (mod L), c = L - 2^252
// (125 bits). Magnitudes shrink 512 -> 385 -> 258 -> 131 -> done; track
// the sign, fix up at the end.

static const uint64_t LC0 = 0x5812631a5cf5d3edull;  // c low word
static const uint64_t LC1 = 0x14def9dea2f79cd6ull;  // c high word
static const uint64_t LW[4] = {0x5812631a5cf5d3edull, 0x14def9dea2f79cd6ull,
                               0, 0x1000000000000000ull};  // L words (LE)

// out = (64-byte little-endian h) mod L, as 32 little-endian bytes
static void sc_reduce64_fast(uint8_t out[32], const uint8_t h[64]) {
    uint64_t x[9] = {0};
    for (int i = 0; i < 8; i++)
        for (int j = 7; j >= 0; j--) x[i] = (x[i] << 8) | h[8 * i + j];
    bool neg = false;
    for (;;) {
        // h1 = x >> 252 (up to 5 words), h0 = x & (2^252 - 1)
        uint64_t h1[5];
        for (int i = 0; i < 5; i++) {
            uint64_t lo = (i + 3 < 9) ? x[i + 3] : 0;
            uint64_t hi = (i + 4 < 9) ? x[i + 4] : 0;
            h1[i] = (lo >> 60) | (hi << 4);
        }
        bool h1z = true;
        for (int i = 0; i < 5; i++) h1z = h1z && h1[i] == 0;
        if (h1z) break;
        uint64_t h0[4] = {x[0], x[1], x[2], x[3] & 0x0FFFFFFFFFFFFFFFull};
        // m1 = h1 * c (<= 7 words)
        uint64_t m1[8] = {0};
        for (int i = 0; i < 5; i++) {
            u128 carry = 0;
            u128 t = (u128)h1[i] * LC0 + m1[i] + carry;
            m1[i] = (uint64_t)t;
            carry = t >> 64;
            t = (u128)h1[i] * LC1 + m1[i + 1] + carry;
            m1[i + 1] = (uint64_t)t;
            carry = t >> 64;
            uint64_t cw = (uint64_t)carry;
            for (int k = i + 2; cw && k < 8; k++) {
                u128 s = (u128)m1[k] + cw;
                m1[k] = (uint64_t)s;
                cw = (uint64_t)(s >> 64);
            }
        }
        // x = |h0 - m1|, sign flips when m1 > h0
        int cmp = 0;
        for (int i = 7; i >= 0 && cmp == 0; i--) {
            uint64_t a = (i < 4) ? h0[i] : 0;
            if (a != m1[i]) cmp = a < m1[i] ? -1 : 1;
        }
        uint64_t borrow = 0;
        for (int i = 0; i < 8; i++) {
            uint64_t a = (i < 4) ? h0[i] : 0;
            uint64_t b = m1[i];
            if (cmp < 0) { uint64_t t = a; a = b; b = t; }
            u128 d = (u128)a - b - borrow;
            x[i] = (uint64_t)d;
            borrow = (uint64_t)(d >> 64) ? 1 : 0;
        }
        x[8] = 0;
        if (cmp < 0) neg = !neg;
        if (cmp == 0) { neg = false; break; }
    }
    uint64_t r[4] = {x[0], x[1], x[2], x[3]};
    bool rz = (r[0] | r[1] | r[2] | r[3]) == 0;
    if (neg && !rz) {  // r := L - r  (r < 2^252 < L)
        uint64_t borrow = 0;
        for (int i = 0; i < 4; i++) {
            u128 d = (u128)LW[i] - r[i] - borrow;
            r[i] = (uint64_t)d;
            borrow = (uint64_t)(d >> 64) ? 1 : 0;
        }
    }
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++) out[8 * i + j] = uint8_t(r[i] >> (8 * j));
}

// -------------------------------------------- batch prep for the TPU path
//
// The host side of ops/ed25519_batch.py: per signature, the structural
// checks + SHA-512(R||A||M) mod L + pubkey decompression to -A affine
// extended words. This was 22us/sig of Python (VERDICT round 1 weak #2);
// here it is ~1us/sig across threads. Decompressions are cached (validator
// keys are stable across heights).

namespace {

// One-shot SHA-512 via the system libcrypto when present (its AVX2 code is
// ~2x the portable sha2.h path; prefetched EVP avoids the per-call fetch
// that makes the legacy SHA512() entry slow on OpenSSL 3 — measured 356ns
// vs 767ns per 76-byte hash), falling back to the builtin.
struct EvpSha512Api {
    void* md = nullptr;
    void* (*ctx_new)() = nullptr;
    void (*ctx_free)(void*) = nullptr;
    int (*init)(void*, const void*, void*) = nullptr;
    int (*update)(void*, const void*, size_t) = nullptr;
    int (*final)(void*, unsigned char*, unsigned*) = nullptr;
    bool ok = false;
};

const EvpSha512Api& evp_api() {
    static EvpSha512Api api = [] {
        EvpSha512Api a;
        for (const char* name :
             {"libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"}) {
            void* h = dlopen(name, RTLD_NOW | RTLD_LOCAL);
            if (!h) continue;
            auto fetch = (void* (*)(void*, const char*, const char*))dlsym(
                h, "EVP_MD_fetch");
            a.ctx_new = (void* (*)())dlsym(h, "EVP_MD_CTX_new");
            a.ctx_free = (void (*)(void*))dlsym(h, "EVP_MD_CTX_free");
            a.init = (int (*)(void*, const void*, void*))dlsym(
                h, "EVP_DigestInit_ex");
            a.update = (int (*)(void*, const void*, size_t))dlsym(
                h, "EVP_DigestUpdate");
            a.final = (int (*)(void*, unsigned char*, unsigned*))dlsym(
                h, "EVP_DigestFinal_ex");
            if (fetch && a.ctx_new && a.ctx_free && a.init && a.update &&
                a.final) {
                a.md = fetch(nullptr, "SHA512", nullptr);
                if (a.md) {
                    a.ok = true;
                    return a;
                }
            }
            dlclose(h);
        }
        return EvpSha512Api{};
    }();
    return api;
}

struct ThreadShaCtx {  // RAII so per-call worker threads don't leak ctxs
    void* ctx = nullptr;
    ~ThreadShaCtx() {
        if (ctx) evp_api().ctx_free(ctx);
    }
};

void sha512_oneshot(const uint8_t* data, size_t len, uint8_t out[64]) {
    const EvpSha512Api& api = evp_api();
    if (api.ok) {
        thread_local ThreadShaCtx tc;
        if (!tc.ctx) tc.ctx = api.ctx_new();
        unsigned olen = 0;
        api.init(tc.ctx, api.md, nullptr);
        api.update(tc.ctx, data, len);
        api.final(tc.ctx, out, &olen);
    } else {
        Sha512 sh;
        sh.update(data, len);
        sh.final(out);
    }
}

// pubkey -> 96-byte x||y||t of -A (canonical LE); the sharded keyed-hash
// pattern (incl. junk-key eviction priority) lives in pubcache.h, shared
// with the secp256k1 core.
struct PubCache {
    ShardedPubCache<32, 96> inner;

    // returns true if key decompresses; writes 96 bytes of -A into out
    bool get(const uint8_t pub[32], uint8_t out[96]) {
        return inner.get(pub, out, [](const uint8_t* k, uint8_t* v) {
            Point A;
            if (!pt_frombytes(A, k)) return false;
            Point negA;
            pt_neg(negA, A);
            fe_tobytes(v, negA.X);
            fe_tobytes(v + 32, negA.Y);
            fe_tobytes(v + 64, negA.T);
            return true;
        });
    }
};

PubCache g_pub_cache;

template <typename F>
void prep_parallel_for(size_t n, F f) {
    unsigned hw = std::thread::hardware_concurrency();
    size_t workers = hw ? hw : 1;
    // Each thread costs a spawn/join plus an EVP ctx alloc; only fan out
    // when every worker gets a meaningful chunk.
    if (workers > n / 256) workers = n / 256;
    if (workers <= 1) {
        for (size_t i = 0; i < n; i++) f(i);
        return;
    }
    std::vector<std::thread> ts;
    ts.reserve(workers);
    size_t chunk = (n + workers - 1) / workers;
    for (size_t w = 0; w < workers; w++) {
        size_t lo = w * chunk, hi = lo + chunk < n ? lo + chunk : n;
        if (lo >= hi) break;
        ts.emplace_back([=] {
            for (size_t i = lo; i < hi; i++) f(i);
        });
    }
    for (auto& t : ts) t.join();
}

}  // namespace

// Host-side batch prep, writing the TPU kernel's wire format directly:
// word-transposed (8, stride) uint32 planes (stride = the padded device
// batch; lanes n..stride-1 are left zero). Inputs: pubs n*32, msgs flat +
// offsets[n+1], sigs n*64. out_ax/ay/at = -A affine extended coords,
// out_s = S, out_h = SHA-512(R||A||M) mod L, out_yr = R's y (bit 255
// cleared), out_parity (stride,) = R sign bit, out_mask n (1 = structurally
// valid: A decompresses, S < L, y_R < p).
extern "C" void tm_ed25519_prepare_batch(
    const uint8_t* pubs, const uint8_t* msgs, const uint64_t* offsets,
    const uint8_t* sigs, size_t n, size_t stride,
    uint32_t* out_ax, uint32_t* out_ay, uint32_t* out_at,
    uint32_t* out_s, uint32_t* out_h, uint32_t* out_yr,
    int32_t* out_parity, uint8_t* out_mask) {
    prep_parallel_for(n, [&](size_t i) {
        const uint8_t* pub = pubs + 32 * i;
        const uint8_t* sig = sigs + 64 * i;
        out_mask[i] = 0;
        out_parity[i] = sig[31] >> 7;
        if (!sc_canonical(sig + 32)) return;
        if (!y_canonical(sig)) return;  // strict: reject non-canonical R
        uint8_t yr[32];
        memcpy(yr, sig, 32);
        yr[31] &= 0x7f;
        uint8_t a96[96];
        if (!g_pub_cache.get(pub, a96)) return;
        uint8_t hfull[64];
        uint8_t hred[32];
        size_t mlen = (size_t)(offsets[i + 1] - offsets[i]);
        uint8_t stackbuf[1024];
        if (64 + mlen <= sizeof stackbuf) {
            memcpy(stackbuf, sig, 32);
            memcpy(stackbuf + 32, pub, 32);
            memcpy(stackbuf + 64, msgs + offsets[i], mlen);
            sha512_oneshot(stackbuf, 64 + mlen, hfull);
        } else {
            std::vector<uint8_t> buf(64 + mlen);
            memcpy(buf.data(), sig, 32);
            memcpy(buf.data() + 32, pub, 32);
            memcpy(buf.data() + 64, msgs + offsets[i], mlen);
            sha512_oneshot(buf.data(), buf.size(), hfull);
        }
        sc_reduce64_fast(hred, hfull);
        auto scatter = [&](uint32_t* plane, const uint8_t* src) {
            for (int w = 0; w < 8; w++) {
                uint32_t v;
                memcpy(&v, src + 4 * w, 4);  // little-endian host assumed
                plane[(size_t)w * stride + i] = v;
            }
        };
        scatter(out_ax, a96);
        scatter(out_ay, a96 + 32);
        scatter(out_at, a96 + 64);
        scatter(out_s, sig + 32);
        scatter(out_h, hred);
        scatter(out_yr, yr);
        out_mask[i] = 1;
    });
}

// Structural checks + h = SHA512(R||A||M) mod L. False => reject. The
// pubkey is NOT decompressed here: h hashes the raw A bytes, and a
// per-key table-cache hit (fetch only happens on a miss, fetch_nega)
// never needs the point at all.
static bool ed_parse(const uint8_t pub[32], const uint8_t* msg,
                     size_t msglen, const uint8_t sig[64], uint8_t h[32]) {
    if (!sc_canonical(sig + 32)) return false;  // non-canonical s
    Point Rpt;
    if (!pt_frombytes(Rpt, sig)) return false;  // R must be a valid point

    uint8_t hfull[64];
    Sha512 sh;
    sh.update(sig, 32);
    sh.update(pub, 32);
    sh.update(msg, msglen);
    sh.final(hfull);
    sc_reduce64_fast(h, hfull);
    return true;
}

// -A via the decompression cache (a stable validator set pays the sqrt
// once per key, not once per vote — g_pub_cache is shared with the TPU
// batch-prep path, which caches the same -A representation)
static bool fetch_nega(const uint8_t pub[32], Point& negA) {
    uint8_t nega_b[96];
    if (!g_pub_cache.get(pub, nega_b)) return false;
    fe_frombytes(negA.X, nega_b);
    fe_frombytes(negA.Y, nega_b + 32);
    fe_one(negA.Z);
    fe_frombytes(negA.T, nega_b + 64);
    return true;
}

// per-pubkey Niels (affine precomputed) wNAF table cache: 8 odd
// multiples of -A, 960 B/key. Steady-state validators hit it every
// height, skipping the table build AND switching the A stream from
// unified extended adds to mixed adds. Filled only by the batched core
// (affine normalization comes ~free there, from the shared inversion).
static ShardedPubCache<32, 8 * sizeof(Niels)> a_tab_cache(1024);

// A-stream table application, generic over table representation
static void a_apply(Point& P, const Point* tab, int e) {
    if (e > 0) {
        pt_add(P, P, tab[(e - 1) >> 1]);
    } else if (e < 0) {
        Point n;
        pt_neg(n, tab[(-e - 1) >> 1]);
        pt_add(P, P, n);
    }
}

static void a_apply(Point& P, const Niels* tab, int e) {
    if (e > 0) {
        pt_madd(P, P, tab[(e - 1) >> 1]);
    } else if (e < 0) {
        pt_msub(P, P, tab[(-e - 1) >> 1]);
    }
}

// P = [s]B + [h](-A): interleaved Strauss, wNAF(8) over the static
// Niels B table + wNAF(5) over the per-key table (extended coords when
// built one-off; cached Niels on the steady-state path).
template <typename AT>
static void ed_strauss(Point& P, const uint8_t s_bytes[32],
                       const uint8_t h[32], const AT a_tab[8]) {
    int8_t ns[257], nh[257];
    int ls = wnaf_le(ns, s_bytes, 8);
    int lh = wnaf_le(nh, h, 5);
    int top = (ls > lh ? ls : lh) - 1;
    pt_identity(P);
    for (int i = top; i >= 0; i--) {
        pt_double(P, P);
        int d = ns[i];
        if (d > 0) {
            pt_madd(P, P, B_TAB[(d - 1) >> 1]);
        } else if (d < 0) {
            pt_msub(P, P, B_TAB[(-d - 1) >> 1]);
        }
        a_apply(P, a_tab, nh[i]);
    }
}

// wNAF(5) table of odd multiples [1,3,...,15](-A), extended coords
static void build_a_tab(Point a_tab[8], const Point& negA) {
    Point nA2;
    pt_double(nA2, negA);
    a_tab[0] = negA;
    for (int i = 1; i < 8; i++) pt_add(a_tab[i], a_tab[i - 1], nA2);
}

// public entry: 1 valid, 0 invalid. Strict RFC 8032 check, evaluated as
// one interleaved Strauss double-scalar multiplication (see the design
// note above pt_madd for why there is deliberately no RLC batch path).
extern "C" int tm_ed25519_verify(const uint8_t pub[32], const uint8_t* msg,
                                 size_t msglen, const uint8_t sig[64]) {
    Point P;
    uint8_t h[32];
    if (!ed_parse(pub, msg, msglen, sig, h)) return 0;
    ensure_b_table();
    Niels cached[8];
    if (a_tab_cache.lookup(pub, reinterpret_cast<uint8_t*>(cached))) {
        // steady-state key: the point is never even decompressed
        ed_strauss(P, sig + 32, h, cached);
    } else {
        Point negA, a_tab[8];
        if (!fetch_nega(pub, negA)) return 0;
        build_a_tab(a_tab, negA);
        ed_strauss(P, sig + 32, h, a_tab);
    }
    uint8_t enc[32];
    pt_tobytes(enc, P);
    return memcmp(enc, sig, 32) == 0 ? 1 : 0;
}

// Batched range core (batch.cpp shards [lo,hi) across threads), phased
// like the secp one:
//   A. parse + per-key Niels-table cache lookup;
//   B. for missed keys, build the extended table and batch-normalize all
//      of them to Niels form with ONE shared inversion (minv.h), then
//      cache. The unified Edwards addition law is complete for ed25519's
//      parameters (d non-square), so no table entry can have Z = 0 — the
//      inversion chain cannot be poisoned;
//   C. Strauss loops, all A streams on Niels tables (mixed adds);
//   D. final encode-compare with its own shared inversion.
// Verdicts are bit-identical to the single-shot entry.
extern "C" void tm_ed25519_verify_range(const uint8_t* pubs,
                                        const uint8_t* msgs,
                                        const uint64_t* offsets,
                                        const uint8_t* sigs, size_t lo,
                                        size_t hi, uint8_t* out) {
    ensure_b_table();
    constexpr size_t CH = 64;
    Point P[CH];
    Point a_ext[CH][8];
    Niels a_niels[CH][8];
    uint8_t hbuf[CH][32];
    bool valid[CH], tab_hit[CH];
    Fe zinvs[CH * 8];
    Fe* zptr[CH * 8];
    for (size_t base = lo; base < hi; base += CH) {
        const size_t m = (hi - base < CH) ? (hi - base) : CH;
        // ---- A: parse + table-cache probe (decompression is lazy)
        for (size_t i = 0; i < m; i++) {
            const size_t g = base + i;
            valid[i] = ed_parse(pubs + 32 * g, msgs + offsets[g],
                                (size_t)(offsets[g + 1] - offsets[g]),
                                sigs + 64 * g, hbuf[i]);
            if (valid[i])
                tab_hit[i] = a_tab_cache.lookup(
                    pubs + 32 * g, reinterpret_cast<uint8_t*>(a_niels[i]));
        }
        // ---- B: decompress + build + batch-normalize missed tables
        size_t nz = 0;
        for (size_t i = 0; i < m; i++) {
            if (!valid[i] || tab_hit[i]) continue;
            Point negA;  // lazy: only missed keys decompress
            if (!fetch_nega(pubs + 32 * (base + i), negA)) {
                valid[i] = false;
                continue;
            }
            build_a_tab(a_ext[i], negA);
            for (int j = 0; j < 8; j++) zptr[nz++] = &a_ext[i][j].Z;
        }
        Fe one;
        fe_one(one);
        batch_invert(zptr, zinvs, nz, one, fe_mul, fe_invert);
        nz = 0;
        for (size_t i = 0; i < m; i++) {
            if (!valid[i] || tab_hit[i]) continue;
            for (int j = 0; j < 8; j++) {
                Fe x, y, xy;
                fe_mul(x, a_ext[i][j].X, zinvs[nz]);
                fe_mul(y, a_ext[i][j].Y, zinvs[nz]);
                nz++;
                Niels& e = a_niels[i][j];
                fe_add(e.yplusx, y, x);
                fe_carry(e.yplusx);
                fe_sub(e.yminusx, y, x);
                fe_carry(e.yminusx);
                fe_mul(xy, x, y);
                fe_mul(xy, xy, FE_D);
                fe_add(e.t2d, xy, xy);
                fe_carry(e.t2d);
            }
            a_tab_cache.put(pubs + 32 * (base + i),
                            reinterpret_cast<const uint8_t*>(a_niels[i]));
        }
        // ---- C: Strauss loops (all-Niels A streams)
        for (size_t i = 0; i < m; i++) {
            if (!valid[i]) continue;
            const size_t g = base + i;
            ed_strauss(P[i], sigs + 64 * g + 32, hbuf[i], a_niels[i]);
            // final-encode chain guard (see range-core note: Z is never
            // 0 for complete Edwards addition; cheap canonical check
            // keeps the shared inversion below unpoisonable regardless)
            if (fe_iszero(P[i].Z)) valid[i] = false;
        }
        // ---- D: batch encode-compare (one shared inversion)
        size_t nv = 0;
        for (size_t i = 0; i < m; i++)
            if (valid[i]) zptr[nv++] = &P[i].Z;
        batch_invert(zptr, zinvs, nv, one, fe_mul, fe_invert);
        nv = 0;
        for (size_t i = 0; i < m; i++) {
            if (!valid[i]) {
                out[base + i] = 0;
                continue;
            }
            Fe x, y;
            fe_mul(x, P[i].X, zinvs[nv]);
            fe_mul(y, P[i].Y, zinvs[nv]);
            nv++;
            uint8_t enc[32];
            fe_tobytes(enc, y);
            enc[31] ^= uint8_t(fe_parity(x) << 7);
            out[base + i] = memcmp(enc, sigs + 64 * (base + i), 32) == 0;
        }
    }
}

}  // namespace tmnative
