"""Driver benchmark: batched Ed25519 verification throughput per chip.

Measures HONEST end-to-end verification of 10,000-validator commits — the
BASELINE.json north star (reference serial path: one `VerifyBytes` per
CommitSig, types/validator_set.go:609-627, ~150us each on modern x86 per
BASELINE.md → ~6.7k verifies/sec serial baseline).

Honest = every cost included: host prep (SHA-512, reduce, cached
decompress, packing — native C++), host->device transfer, kernel, verdict
fetch. Two workload shapes:

- Throughput: a stream of K back-to-back commits with DISTINCT contents fed
  through `verify_batch` as one stream — the fast-sync / light-client shape
  (SURVEY §3.5 hot loops #3/#4: thousands of commits verified
  back-to-back). The verifier merges the stream into as few device launches
  as possible (kcache.MAX_BUCKET-lane chunks) because every launch pays a
  fixed dispatch cost — ~65 ms per execute on the axon tunnel, which does
  NOT pipeline (measured: 16 queued trivial executes = 64.8 ms/op each) —
  and dispatches chunks ASYNCHRONOUSLY, so the host prep of chunk N+1
  overlaps the device execute of chunk N and verdict fetches batch at the
  end. K is sized to span multiple chunks (r2 VERDICT #2: a single-launch
  stream serializes its whole prep in front of the one execute).
- Latency: one commit, fully synchronous, tunnel round trips included; plus
  commit-verify p50 at 100/1000 validators (the small-batch live path).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics go to stderr.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

N_COMMIT = 10_000         # validators in the north-star commit
N_UNIQUE = 512            # unique keypairs; messages differ per commit
LATENCY_NS = (100, 1000)  # small-validator-count p50 latency sizes; shared
# by the prewarm set and the measurement loop so they cannot drift apart
PIPELINE_K = 39           # back-to-back commits for the throughput number:
# 390k signatures span three MAX_BUCKET chunks, so the stream actually
# exercises the prep/execute overlap (8 commits fit one launch and
# serialize prep in front of it). 39 is chosen so the REMAINDER chunk
# (127,856 lanes) pads to the same 131072 bucket as the full chunks —
# one compiled variant, half the cold-compile exposure on a fresh host.

if os.environ.get("TMTPU_BENCH_SMOKE"):
    # logic smoke test on CPU (the full shapes take minutes of XLA:CPU
    # kernel time): tiny commits, same code paths, numbers meaningless
    N_COMMIT, N_UNIQUE, PIPELINE_K = 96, 16, 3
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
# Serial Go x/crypto/ed25519 verify ~150us/op (BASELINE.md context) ->
# baseline verifies/sec for one CPU core, the reference's actual hot path.
BASELINE_VERIFIES_PER_SEC = 1e6 / 150.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _probe_device(timeout_s: float = 150.0, attempts: int = 3) -> None:
    """Fail fast if the device link is wedged. A dead axon tunnel makes
    every jax RPC — including jax.devices() — hang FOREVER with no error
    (it died mid-run once in round 2); probing in a subprocess with a
    timeout turns an indefinite hang into a quick, diagnosable failure.

    Retries with backoff (round-2 lesson: one transient wedge zeroed the
    whole round's record) — a tunnel that recovers within ~10 min still
    yields a bench number; only a persistently dead link exits."""
    import subprocess

    for attempt in range(1, attempts + 1):
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                check=True,
                capture_output=True,
                text=True,
            )
            return
        except subprocess.TimeoutExpired:
            log(
                f"device probe {attempt}/{attempts} hung >{timeout_s:.0f}s "
                f"— tunnel down?"
            )
        except subprocess.CalledProcessError as e:
            log(
                f"device probe {attempt}/{attempts} failed: "
                f"{e.stderr[-500:]}"
            )
        if attempt < attempts:
            backoff = 30.0 * attempt
            log(f"retrying probe in {backoff:.0f}s")
            time.sleep(backoff)
    log("FATAL: device probe exhausted retries")
    _replay_banked_or_exit()


def _replay_banked_or_exit(bank_dir: str | None = None) -> None:
    """Dead-tunnel fallback: replay the most recent REAL TPU measurement
    banked by a tunnel window earlier in the round (rounds 2-4 lesson: the
    tunnel is frequently dead at the driver's end-of-round run even when
    it answered mid-round, which turned real mid-round measurements into
    rc=3/parsed=null records three rounds running). The replayed line is
    explicitly labelled: metric gets a "_banked" suffix and the record
    carries measured_at_utc + source, so it can never be mistaken for a
    live end-of-round measurement. No banked number -> a CPU-only
    degraded measurement (ISSUE 2: BENCH_*.json must never again record
    "parsed": null with rc=3 and no artifact)."""
    if bank_dir is None:
        bank_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tunnel_watch"
        )
    for name in ("banked_headline.json", "banked_quick.json"):
        path = os.path.join(bank_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if rec.get("platform") != "tpu" or "value" not in rec:
            continue
        rec["metric"] = str(rec.get("metric", "ed25519")) + "_banked"
        rec["note"] = (
            "tunnel dead at driver run; replaying the TPU number banked "
            f"at {rec.get('measured_at_utc')} by {name} (see "
            "tunnel_watch/watch.log)"
        )
        log(f"replaying banked TPU measurement from {name}")
        print(json.dumps(rec), flush=True)
        raise SystemExit(0)
    _cpu_degraded_bench()


def _cpu_degraded_bench(n: int = 2048) -> None:
    """Device permanently unreachable and nothing banked: measure the
    device-free CPU verification path and emit a parseable JSON record
    tagged "device": "unavailable" instead of exiting rc=3 with no
    artifact. Deliberately avoids importing jax at all — on a wedged
    tunnel any jax RPC can hang forever (the round-5 failure mode). Any
    failure INSIDE the degraded measurement still emits a minimal JSON
    record: this path exists precisely so the driver never again records
    "parsed": null."""
    rec = {
        # suffixed like the _banked convention above: a CPU-only number
        # must never be mistakable for TPU per-chip throughput by a
        # consumer keying on the metric name alone
        "metric": "ed25519_e2e_verifies_per_sec_per_chip_cpu_degraded",
        "value": 0.0,
        "unit": "verifies/s",
        "vs_baseline": 0.0,
        "device": "unavailable",
        "note": (
            "device probe exhausted retries and no banked TPU number "
            f"exists; CPU-only degraded measurement ({n} sigs, no jax)"
        ),
    }
    try:
        os.environ.setdefault("TMTPU_NO_AUTO_OPS", "1")  # keep jax out
        from tendermint_tpu.crypto import batch as cb
        from tendermint_tpu.crypto import ed25519

        try:
            from tendermint_tpu.crypto import native

            native.register()  # threaded C++ batch core when available
        except Exception as e:  # noqa: BLE001 — serial python still measures
            log(f"native backend unavailable for degraded bench: {e!r}")
        n_unique = 256
        privs = [ed25519.gen_priv_key() for _ in range(n_unique)]
        msg = b"degraded cpu bench vote"
        triples = []
        for i in range(n):
            p = privs[i % n_unique]
            triples.append((p.pub_key(), msg, p.sign(msg)))
        t0 = time.perf_counter()
        ok = cb.verify_batch(triples)
        dt = time.perf_counter() - t0
        assert all(ok), "CPU path rejected valid signatures"
        rate = n / dt
        rec["value"] = round(rate, 1)
        rec["vs_baseline"] = round(rate / BASELINE_VERIFIES_PER_SEC, 2)
        log(f"degraded CPU bench: {rate:,.0f} verifies/s over {n} sigs")
    except Exception as e:  # noqa: BLE001 — a broken CPU stack must still
        # yield an artifact, never an unhandled traceback with no JSON
        rec["error"] = repr(e)
        log(f"degraded CPU bench itself failed: {e!r}")
    print(json.dumps(rec), flush=True)
    raise SystemExit(0)


def _supervised(started_at: float) -> None:
    """Run the measurement in a watchdogged CHILD process group.

    A tunnel that answers the probe can still wedge during the first
    compile/execute RPC (observed this round: probe OK at 03:16, dead
    ~1 min later) — and a wedged jax RPC hangs FOREVER, turning the
    driver's capture into an external kill with no JSON. The parent
    enforces deadlines and, because the child prints its one JSON line
    the moment the headline number exists, a child that hangs in the
    post-headline diagnostics still yields rc=0 with the captured line.
    """
    import signal
    import subprocess
    import threading

    env = dict(os.environ)
    env["TMTPU_BENCH_CHILD"] = "1"
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        env=env,
        start_new_session=True,  # killpg reaps a wedged jax cleanly
        text=True,
    )

    def _forward_kill(signum, _frame):
        # the child runs in its own session, outside any process-group
        # kill aimed at THIS process (tunnel_watch run_step sends TERM to
        # the group on step timeout): forward it or the wedged-jax child
        # survives orphaned, holding the tunnel against every retry.
        # A result captured before the external kill still counts.
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        if json_line:
            print(json_line[0], flush=True)
            raise SystemExit(0)
        raise SystemExit(128 + signum)

    json_line: list[str] = []
    # bound BEFORE the handlers are installed: a signal landing between
    # registration and binding would NameError inside _forward_kill,
    # losing both the group kill and the captured-result exit (ADVICE r4)
    signal.signal(signal.SIGTERM, _forward_kill)
    signal.signal(signal.SIGINT, _forward_kill)

    def _reader() -> None:
        assert child.stdout is not None
        for line in child.stdout:
            line = line.strip()
            if line.startswith("{"):
                json_line.append(line)

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    # The WHOLE process — probe (worst case 3x150s + backoffs = 540s),
    # compile, measurement, post-JSON grace — must finish inside the
    # smallest external step timeout (tunnel_watch gives bench 1800s),
    # or the external group-kill discards an already-captured JSON line.
    # The deadline is therefore anchored at process START, not here: a
    # slow probe eats compile budget instead of overrunning the window.
    grace_after_json = float(os.environ.get("TMTPU_BENCH_JSON_GRACE_S", 120))
    total_budget = float(os.environ.get("TMTPU_BENCH_TOTAL_S", 1700))
    deadline = started_at + max(60.0, total_budget - grace_after_json)
    if "TMTPU_BENCH_DEADLINE_S" in os.environ:  # test hook
        deadline = time.monotonic() + float(os.environ["TMTPU_BENCH_DEADLINE_S"])
    json_seen_at = None
    while True:
        if child.poll() is not None:
            break
        now = time.monotonic()
        if json_line and json_seen_at is None:
            json_seen_at = now
        if json_seen_at is not None:
            if now - json_seen_at > grace_after_json:
                log("child hung after emitting JSON — killing group, "
                    "result kept")
                break
        elif now > deadline:
            log("FATAL: measurement exceeded deadline — tunnel wedged?")
            break
        time.sleep(2.0)
    if child.poll() is None:
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        child.wait()
    t.join(timeout=10.0)
    if json_line:
        print(json_line[0], flush=True)
        raise SystemExit(0)
    # no JSON captured: nonzero regardless of child rc (a 0 here would
    # hand the driver an empty success; a signal-death negative rc is
    # normalized — the driver keys on small positive codes)
    rc = child.returncode
    raise SystemExit(rc if isinstance(rc, int) and 0 < rc < 126 else 3)


def main() -> None:
    smoke = bool(os.environ.get("TMTPU_BENCH_SMOKE"))
    # FORCE_SUPERVISE exercises the watchdog wrapper on CPU (tests)
    if not smoke or os.environ.get("TMTPU_BENCH_FORCE_SUPERVISE"):
        if not os.environ.get("TMTPU_BENCH_CHILD"):
            started_at = time.monotonic()
            if not smoke:
                _probe_device()
            _supervised(started_at)
            return  # unreachable (SystemExit above); keeps intent clear
    if os.environ.get("TMTPU_BENCH_TEST_HANG") == "pre":
        time.sleep(3600)  # watchdog test hook: wedged-compile simulation
    import jax

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.libs import trace as tmtrace
    from tendermint_tpu.ops import ed25519_batch, kcache

    # TMTPU_TRACE_JSONL=<path>: export every device span (dispatch/fetch
    # latency, bucket occupancy) as the same trace JSONL a node writes
    tmtrace.install_export_from_env()

    kcache.enable_persistent_cache()
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")
    # No background warm-up subprocess mid-measurement: on a tunneled
    # device a second process's compile CONTENDS with the foreground RPC
    # stream (measured: a 20 s stall on the first verify). A node wants
    # that background warm-up (it saves the NEXT process minutes of
    # compile); a benchmark wants clean steady-state numbers.
    kcache.suppress_background_warm()

    # N_UNIQUE real keypairs tiled to N_COMMIT (device work per lane is
    # data-independent); K distinct per-commit messages, all pre-signed.
    privs = [ed25519.gen_priv_key() for _ in range(N_UNIQUE)]
    pubs_u = [p.pub_key().bytes() for p in privs]
    reps = -(-N_COMMIT // N_UNIQUE)
    pubs = (pubs_u * reps)[:N_COMMIT]
    commits = []
    for k in range(PIPELINE_K):
        msg = b"bench vote h=%05d" % k
        sigs = [p.sign(msg) for p in privs]
        commits.append((pubs, [msg] * N_COMMIT, (sigs * reps)[:N_COMMIT]))

    # -- host prep: cold valset (empty decompression cache) vs warm --------
    ed25519_batch._cache._d.clear()
    t0 = time.perf_counter()
    packed, mask = ed25519_batch.prepare_batch(*commits[0])
    cold_prep_s = time.perf_counter() - t0
    assert packed is not None and mask.all()
    t0 = time.perf_counter()
    packed, _ = ed25519_batch.prepare_batch(*commits[0])
    warm_prep_s = time.perf_counter() - t0
    log(
        f"host prep 10k (native): cold valset {cold_prep_s * 1e3:.1f} ms, "
        f"warm {warm_prep_s * 1e3:.1f} ms"
    )

    fn = kcache.get_verify_fn(packed.shape[1])
    t0 = time.perf_counter()
    out = np.asarray(fn(*(jax.device_put(b, dev) for b in ed25519_batch.split(packed))))
    log(f"compile + first run: {time.perf_counter() - t0:.1f}s")
    assert out[:N_COMMIT].all(), "kernel rejected valid sigs"

    # -- stream throughput: K distinct commits through verify_batch --------
    # (compile the stream chunk buckets outside the timed region; a node
    # prewarms them the same way at start — kcache.prewarm)
    merged = [sum((c[i] for c in commits), []) for i in range(3)]
    n_total = len(merged[0])
    warm_buckets = set()
    for lo in range(0, n_total, kcache.MAX_BUCKET):
        warm_buckets.add(
            ed25519_batch._pad_to_bucket(min(kcache.MAX_BUCKET, n_total - lo))
        )
    # also the single-commit / small-commit latency buckets measured below:
    # without these, their first call pays a ~20s compile inside the timed
    # region and the "cold valset" label lies (it should measure the key
    # transfer, not XLA)
    warm_buckets |= {
        ed25519_batch._pad_to_bucket(n) for n in (*LATENCY_NS, N_COMMIT)
    }
    kcache.prewarm(sorted(warm_buckets), background=False)

    # cold stream: key blocks transferred; warm stream: keys device-resident
    # (the fast-sync steady state — the same valset signs every height)
    ed25519_batch._dev_keys._d.clear()
    t0 = time.perf_counter()
    with tmtrace.span("bench_stream", phase="cold", commits=PIPELINE_K):
        ok = ed25519_batch.verify_batch(*merged)
    cold_stream_s = time.perf_counter() - t0
    assert all(ok), "stream verify rejected valid sigs"
    merged2 = list(merged)
    merged2[1] = [b"bench vote warm %05d" % (i // N_COMMIT) for i in range(n_total)]
    # re-sign under the warm messages so the warm stream is distinct work
    warm_sigs = []
    for k in range(PIPELINE_K):
        msg = b"bench vote warm %05d" % k
        sigs_k = [p.sign(msg) for p in privs]
        warm_sigs.extend((sigs_k * reps)[:N_COMMIT])
    merged2[2] = warm_sigs
    t0 = time.perf_counter()
    with tmtrace.span("bench_stream", phase="warm", commits=PIPELINE_K):
        ok = ed25519_batch.verify_batch(*merged2)
    stream_s = time.perf_counter() - t0
    assert all(ok), "warm stream verify rejected valid sigs"
    log(
        f"{PIPELINE_K}x10k-commit stream, cold valset: "
        f"{cold_stream_s * 1e3:.1f} ms ({n_total / cold_stream_s:,.0f}/s)"
    )
    per_commit_s = stream_s / PIPELINE_K
    rate = n_total / stream_s
    log(
        f"{PIPELINE_K}x10k-commit stream, warm valset: {stream_s * 1e3:.1f} ms "
        f"({per_commit_s * 1e3:.2f} ms/commit, {rate:,.0f} verifies/sec/chip; "
        f"north star <5ms/commit on v4-8)"
    )
    # the ONE stdout line goes out as soon as the headline number exists:
    # the tunnel can wedge mid-run (jax RPCs then hang forever — it died
    # between sections once in round 2), and the remaining measurements
    # below are stderr diagnostics that must not be able to cost the
    # recorded result
    headline = {
        "metric": "ed25519_e2e_verifies_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "verifies/s",
        "vs_baseline": round(rate / BASELINE_VERIFIES_PER_SEC, 2),
    }
    print(json.dumps(headline), flush=True)
    if dev.platform == "tpu":
        # bank the real-TPU headline so a later driver run against a dead
        # tunnel can replay it (labelled) instead of recording null
        try:
            from benchmarks.quick_bench import BANK_PATH, bank

            headline.update(
                platform="tpu",
                device_kind=str(dev.device_kind),
                measured_at_utc=time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                source=f"bench.py {PIPELINE_K}x10k warm stream",
            )
            bank(
                headline,
                os.path.join(
                    os.path.dirname(BANK_PATH), "banked_headline.json"
                ),
            )
        except Exception as e:  # noqa: BLE001 — banking must not cost the run
            log(f"banking failed (non-fatal): {e!r}")
    if os.environ.get("TMTPU_BENCH_TEST_HANG") == "post":
        time.sleep(3600)  # watchdog test hook: post-headline wedge

    # -- single-commit latency (fully sync, includes tunnel round trip) ----
    # verify_batch end to end: prep + device-key-cache lookup + launch +
    # fetch. First call is the cold-valset path (key block transferred);
    # repeats hit the resident key block like a live validator does.
    ed25519_batch._dev_keys._d.clear()
    for label, ks in (("cold", range(1)), ("warm keys", range(1, 3))):
        lat = []
        for k in ks:
            t0 = time.perf_counter()
            ok = ed25519_batch.verify_batch(*commits[k % PIPELINE_K])
            lat.append(time.perf_counter() - t0)
            assert all(ok)
        log(
            f"single 10k-commit latency ({label}, sync): "
            f"{min(lat) * 1e3:.1f} ms"
        )

    # -- commit-verify p50 at small validator counts (latency metric) ------
    for n in LATENCY_NS:
        samples = []
        for k in range(5):
            p, m, s = commits[k % PIPELINE_K]
            t0 = time.perf_counter()
            ok_n = ed25519_batch.verify_batch(p[:n], m[:n], s[:n])
            samples.append(time.perf_counter() - t0)
            assert all(ok_n)
        log(
            f"commit-verify p50 @ {n} validators: "
            f"{statistics.median(samples) * 1e3:.1f} ms (sync, tunnel incl.)"
        )


if __name__ == "__main__":
    main()
