"""Driver benchmark: batched Ed25519 verification throughput per chip.

Measures the end-to-end device verification of a 10,000-validator commit —
the BASELINE.json north star (reference serial path: one `VerifyBytes` per
CommitSig, types/validator_set.go:609-627, ~150 us each on modern x86 per
x/crypto context in BASELINE.md → ~6.7k verifies/sec serial baseline).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics go to stderr.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

N_COMMIT = 10_000         # validators in the north-star commit
N_UNIQUE = 512            # unique real signatures; tiled to N_COMMIT
# Serial Go x/crypto/ed25519 verify ~150us/op (BASELINE.md context) →
# baseline verifies/sec for one CPU core, the reference's actual hot path.
BASELINE_VERIFIES_PER_SEC = 1e6 / 150.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from tendermint_tpu.ops import ed25519_batch
    from tendermint_tpu.utils import make_sig_batch

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    # Real signatures (unique keys + messages), tiled to commit size; device
    # work per lane is data-independent so tiling measures true throughput.
    pubs, msgs, sigs = make_sig_batch(N_UNIQUE, msg_prefix=b"bench vote ")
    reps = -(-N_COMMIT // N_UNIQUE)
    pubs = (pubs * reps)[:N_COMMIT]
    msgs = (msgs * reps)[:N_COMMIT]
    sigs = (sigs * reps)[:N_COMMIT]

    t0 = time.perf_counter()
    inputs, mask = ed25519_batch.prepare_batch(pubs, msgs, sigs)
    host_prep_s = time.perf_counter() - t0
    assert inputs is not None and mask.all()
    log(f"host prep (hash+decompress+limbs) for {N_COMMIT}: {host_prep_s:.3f}s")

    placed = {k: jax.device_put(v, dev) for k, v in inputs.items()}

    t0 = time.perf_counter()
    out = np.asarray(ed25519_batch.verify_kernel(**placed))
    log(f"compile + first run: {time.perf_counter() - t0:.1f}s")
    assert out[:N_COMMIT].all(), "kernel rejected valid sigs"

    # Honest pipeline timing: fresh host->device transfer of the packed
    # words + kernel + device->host verdict fetch per iteration. (Under the
    # axon tunnel, block_until_ready does not guarantee completion and
    # repeat-identical launches can be result-cached — np.asarray of the
    # output is the reliable sync point.)
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        fresh = {k: jax.device_put(v, dev) for k, v in inputs.items()}
        out = np.asarray(ed25519_batch.verify_kernel(**fresh))
    per_commit_s = (time.perf_counter() - t0) / iters

    rate = N_COMMIT / per_commit_s
    log(
        f"10k-validator commit verify: {per_commit_s * 1e3:.2f} ms "
        f"({rate:,.0f} verifies/sec/chip; north star <5ms on v4-8)"
    )
    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verifies_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "verifies/s",
                "vs_baseline": round(rate / BASELINE_VERIFIES_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
