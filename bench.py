"""Driver benchmark: batched Ed25519 verification throughput per chip.

Measures HONEST end-to-end verification of 10,000-validator commits — the
BASELINE.json north star (reference serial path: one `VerifyBytes` per
CommitSig, types/validator_set.go:609-627, ~150us each on modern x86 per
BASELINE.md → ~6.7k verifies/sec serial baseline).

Honest = every cost included: host prep (SHA-512, scalar reduce, cached
decompress, packing — native C++), host->device transfer, kernel, verdict
fetch. Throughput is measured over K back-to-back commits with DISTINCT
contents (prep runs serially in the loop; device launches pipeline, as they
do in a syncing node), because the axon tunnel adds ~70ms of round-trip
latency per synchronous fetch that a pipelined consumer does not pay.
Single-commit latency (fully synchronous, tunnel included) is reported on
stderr alongside cold/warm prep and the 100/1000-validator p50s.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics go to stderr.
"""
from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np

N_COMMIT = 10_000         # validators in the north-star commit
N_UNIQUE = 512            # unique keypairs; messages differ per commit
PIPELINE_K = 8            # back-to-back commits for the throughput number
# Serial Go x/crypto/ed25519 verify ~150us/op (BASELINE.md context) ->
# baseline verifies/sec for one CPU core, the reference's actual hot path.
BASELINE_VERIFIES_PER_SEC = 1e6 / 150.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.ops import ed25519_batch, kcache
    from tendermint_tpu.utils import make_sig_batch

    kcache.enable_persistent_cache()
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    # N_UNIQUE real keypairs tiled to N_COMMIT (device work per lane is
    # data-independent); K distinct per-commit messages, all pre-signed.
    privs = [ed25519.gen_priv_key() for _ in range(N_UNIQUE)]
    pubs_u = [p.pub_key().bytes() for p in privs]
    reps = -(-N_COMMIT // N_UNIQUE)
    pubs = (pubs_u * reps)[:N_COMMIT]
    commits = []
    for k in range(PIPELINE_K):
        msg = b"bench vote h=%05d" % k
        sigs = [p.sign(msg) for p in privs]
        commits.append((pubs, [msg] * N_COMMIT, (sigs * reps)[:N_COMMIT]))

    # -- host prep: cold valset (empty decompression cache) vs warm --------
    ed25519_batch._cache._d.clear()
    t0 = time.perf_counter()
    inputs, mask = ed25519_batch.prepare_batch(*commits[0])
    cold_prep_s = time.perf_counter() - t0
    assert inputs is not None and mask.all()
    t0 = time.perf_counter()
    inputs, _ = ed25519_batch.prepare_batch(*commits[0])
    warm_prep_s = time.perf_counter() - t0
    log(
        f"host prep 10k (native): cold valset {cold_prep_s * 1e3:.1f} ms, "
        f"warm {warm_prep_s * 1e3:.1f} ms"
    )

    fn = kcache.get_verify_fn(inputs["s_w"].shape[1])
    t0 = time.perf_counter()
    out = np.asarray(fn(**{k: jax.device_put(v, dev) for k, v in inputs.items()}))
    log(f"compile + first run: {time.perf_counter() - t0:.1f}s")
    assert out[:N_COMMIT].all(), "kernel rejected valid sigs"

    # -- single-commit latency (fully sync, includes tunnel round trip) ----
    lat = []
    for k in range(3):
        t0 = time.perf_counter()
        inputs, _ = ed25519_batch.prepare_batch(*commits[k])
        placed = {k2: jax.device_put(v, dev) for k2, v in inputs.items()}
        out = np.asarray(fn(**placed))
        lat.append(time.perf_counter() - t0)
    log(f"single 10k-commit latency (sync): {min(lat) * 1e3:.1f} ms")

    # -- pipelined throughput: K distinct commits back-to-back -------------
    t0 = time.perf_counter()
    outs = []
    for c in commits:
        inputs, _ = ed25519_batch.prepare_batch(*c)
        placed = {k2: jax.device_put(v, dev) for k2, v in inputs.items()}
        outs.append(fn(**placed))
    for o in outs:
        assert np.asarray(o)[:N_COMMIT].all()
    per_commit_s = (time.perf_counter() - t0) / PIPELINE_K
    rate = N_COMMIT / per_commit_s

    # -- commit-verify p50 at small validator counts (latency metric) ------
    for n in (100, 1000):
        samples = []
        for k in range(5):
            p, m, s = commits[k % PIPELINE_K]
            t0 = time.perf_counter()
            inputs, _ = ed25519_batch.prepare_batch(p[:n], m[:n], s[:n])
            fn_n = kcache.get_verify_fn(inputs["s_w"].shape[1])
            placed = {k2: jax.device_put(v, dev) for k2, v in inputs.items()}
            ok = np.asarray(fn_n(**placed))
            samples.append(time.perf_counter() - t0)
        log(
            f"commit-verify p50 @ {n} validators: "
            f"{statistics.median(samples) * 1e3:.1f} ms (sync, tunnel incl.)"
        )

    log(
        f"10k-commit pipelined end-to-end: {per_commit_s * 1e3:.2f} ms/commit "
        f"({rate:,.0f} verifies/sec/chip; north star <5ms on v4-8)"
    )
    print(
        json.dumps(
            {
                "metric": "ed25519_e2e_verifies_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "verifies/s",
                "vs_baseline": round(rate / BASELINE_VERIFIES_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
